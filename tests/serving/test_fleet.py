"""Fleet observability tests (ISSUE 6): metrics federation (including a
replica DOWN -> partial merge), the ``/fleet/slo`` plane, traceparent
propagation with head-based sampling, and two-tier trace stitching with
injected clock skew — against scriptable stub replicas, then end-to-end over
two real in-process replicas."""

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from paddlenlp_tpu.observability import parse_prometheus_text
from paddlenlp_tpu.observability.tracer import TRACER, SpanTracer, trace_sampled
from paddlenlp_tpu.serving.metrics import MetricsRegistry
from paddlenlp_tpu.serving.router import (
    DOWN,
    RouterServer,
    federate_expositions,
    lint_federation,
)

REQS = "paddlenlp_serving_requests_total"
TTFT = "paddlenlp_serving_ttft_seconds"


def replica_exposition(stop=95.0, engine_error=5.0,
                       buckets=((0.1, 80.0), (1.0, 95.0), ("+Inf", 100.0)),
                       count=100.0, extra=""):
    lines = [
        f"# HELP {REQS} Finished requests by terminal state",
        f"# TYPE {REQS} counter",
        f'{REQS}{{status="stop"}} {stop}',
        f'{REQS}{{status="engine_error"}} {engine_error}',
        f"# HELP {TTFT} Arrival to first token",
        f"# TYPE {TTFT} histogram",
    ]
    lines += [f'{TTFT}_bucket{{le="{le}"}} {c}' for le, c in buckets]
    lines += [f"{TTFT}_count {count}", f"{TTFT}_sum 9.5"]
    if extra:
        lines.append(extra)
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------------ federation
class TestFederation:
    def test_merge_relabels_per_replica(self):
        merged = federate_expositions({
            "r0": replica_exposition(stop=10.0),
            "r1": replica_exposition(stop=20.0),
        })
        fams = parse_prometheus_text(merged)
        assert fams[REQS].value(replica="r0", status="stop") == 10.0
        assert fams[REQS].value(replica="r1", status="stop") == 20.0
        # histogram buckets keep per-replica series, le stays a valid label
        assert fams[TTFT].value(sample_name=f"{TTFT}_bucket",
                                replica="r0", le="0.1") == 80.0
        assert fams[REQS].type == "counter" and fams[REQS].help

    def test_partial_input_is_partial_output(self):
        merged = federate_expositions({"r0": replica_exposition()})
        fams = parse_prometheus_text(merged)
        assert {dict(l)["replica"] for _, l in fams[REQS].samples} == {"r0"}

    def test_lint_clean_on_homogeneous_fleet(self):
        assert lint_federation({"r0": replica_exposition(),
                                "r1": replica_exposition()}) == []

    def test_lint_flags_type_conflict(self):
        conflicting = replica_exposition().replace(
            f"# TYPE {REQS} counter", f"# TYPE {REQS} gauge")
        problems = lint_federation({"r0": replica_exposition(), "r1": conflicting})
        assert any("TYPE conflict" in p and REQS in p for p in problems)

    def test_lint_flags_replica_label_collision(self):
        poisoned = replica_exposition(
            extra='paddlenlp_custom_gauge{replica="oops"} 1')
        problems = lint_federation({"r0": poisoned})
        assert any("replica label" in p for p in problems)

    def test_merged_exposition_is_lintable(self):
        from paddlenlp_tpu.observability import lint_exposition
        merged = federate_expositions({"r0": replica_exposition(),
                                       "r1": replica_exposition()})
        assert lint_exposition(merged) == []

    def test_bucket_lines_in_ascending_le_order(self):
        # lexicographic le ordering ("+Inf" first, "10" before "2.5") breaks
        # strict OpenMetrics consumers — buckets must come out cumulative
        merged = federate_expositions({"r0": replica_exposition(
            buckets=(("0.1", 10.0), ("2.5", 60.0), ("10", 80.0), ("+Inf", 100.0)))})
        les = [line.split('le="')[1].split('"')[0]
               for line in merged.splitlines() if f"{TTFT}_bucket" in line]
        assert les == ["0.1", "2.5", "10", "+Inf"]


# ------------------------------------------------------------------ stub tier
class FleetStub:
    """Replica stub for the fleet planes: /health (with tracer clock + skew),
    /metrics (configurable exposition), /debug/trace (skewed spans), and a
    header-recording /v1/completions."""

    def __init__(self, exposition=None, skew_s=0.0, metrics_status=200,
                 tokens=(7, 8, 9)):
        self.exposition = exposition if exposition is not None else replica_exposition()
        self.skew_s = skew_s
        self.metrics_status = metrics_status
        self.tokens = list(tokens)
        self.seen_headers = []  # traceparent headers from /v1/completions
        self.trace_events = {}  # trace id -> [chrome events]
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _raw(self, code, body, ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._raw(200, json.dumps({
                        "status": "ok",
                        "scheduler": {"inflight": 0},
                        "engine": {"queue_depth": 0},
                        # the replica's tracer clock runs skew_s ahead
                        "now": TRACER.now() + stub.skew_s,
                    }).encode())
                elif self.path == "/metrics":
                    self._raw(stub.metrics_status, stub.exposition.encode(),
                              "text/plain; version=0.0.4")
                elif self.path.startswith("/debug/trace"):
                    from urllib.parse import parse_qs, urlsplit
                    trace = parse_qs(urlsplit(self.path).query).get("trace", [None])[0]
                    self._raw(200, json.dumps({
                        "traceEvents": stub.trace_events.get(trace, []),
                        "otherData": {"dropped_spans": 0},
                    }).encode())
                else:
                    self._raw(404, b"{}")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                stub.seen_headers.append(self.headers.get("X-Pdnlp-Traceparent"))
                cid = f"cmpl-{len(stub.seen_headers)}"
                self._raw(200, json.dumps({
                    "id": cid, "object": "text_completion",
                    "choices": [{"index": 0, "finish_reason": "length",
                                 "token_ids": stub.tokens}],
                    "usage": {"prompt_tokens": len(payload.get("prompt", [])),
                              "completion_tokens": len(stub.tokens)},
                }).encode())

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._httpd.daemon_threads = True
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        self.port = self._httpd.server_address[1]

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def get_json(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def get_text(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


@pytest.fixture
def fleet_router():
    created = []

    def build(stubs, **router_kw):
        registry = MetricsRegistry()
        # private tracer per router (like launch_fleet): sampling marks and
        # rtr-N spans from one test's router must not leak into the next
        router_kw.setdefault("tracer", SpanTracer())
        router = RouterServer(
            [("127.0.0.1", s.port, rid) for rid, s in stubs],
            registry=registry, poll_interval_s=30.0, **router_kw)
        port = router.start_in_thread()
        created.append((router, [s for _, s in stubs]))
        deadline = time.time() + 5
        while (time.time() < deadline
               and any(s.last_poll_t is None for s in router.pool.snapshots())):
            time.sleep(0.005)
        return router, port, registry

    yield build
    for router, stubs in created:
        router.shutdown()
        for s in stubs:
            s.stop()


class TestFleetMetrics:
    def test_fleet_metrics_merges_replicas(self, fleet_router):
        a, b = FleetStub(replica_exposition(stop=10.0)), FleetStub(replica_exposition(stop=20.0))
        router, port, _ = fleet_router([("a", a), ("b", b)])
        status, text = get_text(port, "/fleet/metrics")
        assert status == 200
        fams = parse_prometheus_text(text)
        assert fams[REQS].value(replica="a", status="stop") == 10.0
        assert fams[REQS].value(replica="b", status="stop") == 20.0

    def test_down_replica_partial_merge_not_error(self, fleet_router):
        a, b = FleetStub(), FleetStub()
        router, port, _ = fleet_router([("a", a), ("b", b)])
        router.pool.get("b").state = DOWN
        status, text = get_text(port, "/fleet/metrics")
        assert status == 200  # partial beats nothing during an incident
        fams = parse_prometheus_text(text)
        assert {dict(l)["replica"] for _, l in fams[REQS].samples} == {"a"}

    def test_unreachable_scrape_skipped_and_counted(self, fleet_router):
        a, b = FleetStub(), FleetStub(metrics_status=500)
        router, port, registry = fleet_router([("a", a), ("b", b)])
        status, _ = get_text(port, "/fleet/metrics")
        assert status == 200
        err = registry.get("paddlenlp_router_fleet_scrape_errors_total")
        assert err.value(replica="b") == 1.0

    def test_unparseable_exposition_skipped_not_500(self, fleet_router):
        # a 200 body that isn't Prometheus text (port reused by another
        # process, truncated read): skipped like a failed scrape, the merge
        # stays partial — federation never 500s the whole fleet
        a, b = FleetStub(), FleetStub(exposition="<html>not metrics</html>")
        router, port, registry = fleet_router([("a", a), ("b", b)])
        status, text = get_text(port, "/fleet/metrics")
        assert status == 200
        fams = parse_prometheus_text(text)
        assert {dict(l)["replica"] for _, l in fams[REQS].samples} == {"a"}
        err = registry.get("paddlenlp_router_fleet_scrape_errors_total")
        assert err.value(replica="b") == 1.0
        status, rep = get_json(port, "/fleet/slo")
        assert status == 200
        assert rep["replicas"] == ["a"] and rep["skipped"] == ["b"]

    def test_fleet_slo_matches_hand_computed(self, fleet_router):
        # each replica: 100 finished, 5 engine_error; threshold 1.0 on a
        # bucket bound -> 5 TTFT violations per replica
        a, b = FleetStub(), FleetStub()
        router, port, _ = fleet_router([("a", a), ("b", b)])
        status, rep = get_json(port, "/fleet/slo")
        assert status == 200
        assert sorted(rep["replicas"]) == ["a", "b"] and rep["skipped"] == []
        assert rep["totals"]["total"] == 200.0 and rep["totals"]["errors"] == 10.0
        widest = rep["windows"]["3600s"]
        assert widest["availability"] == pytest.approx(1 - 10 / 200)
        # err rate 0.05 over the default 0.999 objective: burning 50x budget
        assert widest["availability_burn_rate"] == pytest.approx(0.05 / 0.001)
        assert widest["ttft_violation_rate"] == pytest.approx(10 / 200)
        # the paddlenlp_slo_* series landed on the router's own /metrics
        _, text = get_text(port, "/metrics")
        fams = parse_prometheus_text(text)
        assert fams["paddlenlp_slo_availability"].value(window="3600s") == \
            pytest.approx(0.95)

    def test_fleet_slo_partial_on_down_replica(self, fleet_router):
        a, b = FleetStub(), FleetStub()
        router, port, _ = fleet_router([("a", a), ("b", b)])
        router.pool.get("b").state = DOWN
        status, rep = get_json(port, "/fleet/slo")
        assert status == 200
        assert rep["replicas"] == ["a"] and rep["skipped"] == ["b"]
        assert rep["totals"]["total"] == 100.0


class TestTraceparentPropagation:
    def test_header_carries_rid_and_sampling(self, fleet_router):
        a = FleetStub()
        router, port, _ = fleet_router([("a", a)], trace_sample_every=8)
        rids = []
        for _ in range(16):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request("POST", "/v1/completions",
                         body=json.dumps({"prompt": [1, 2, 3], "max_tokens": 3}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            conn.close()
            assert resp.status == 200
            rids.append(body["id"])
        assert all(r.startswith("rtr-") for r in rids)
        assert len(a.seen_headers) == 16
        for rid, header in zip(rids, a.seen_headers):
            tid, parent, sampled = header.split(";")[0], None, None
            assert tid == rid
            assert f"parent={rid}@router" in header
            # the router made the 1-in-8 decision ONCE and propagated it
            want = trace_sampled(rid, 8)
            assert f"sampled={1 if want else 0}" in header
        # 1-in-8 over 16 sequential ids: strictly fewer sampled than not
        sampled_n = sum(1 for r in rids if trace_sampled(r, 8))
        assert 0 < sampled_n < len(rids) / 4


class TestStitchedTrace:
    SKEW = 5.0

    def _seed_two_tier_trace(self, router, stub, rid):
        """One request's spans in both tiers, the replica's on a clock SKEW
        seconds ahead of the router's."""
        t0 = router.tracer.now()
        router.tracer.add_span("router_request", t0, 1.0, cat="router", trace=rid)
        # replica events in REPLICA time: skewed ahead; raw merge would put
        # them outside the router span entirely
        stub.trace_events[rid] = [
            {"name": "queue", "cat": "request", "ph": "X",
             "ts": (t0 + self.SKEW + 0.1) * 1e6, "dur": 0.1e6, "pid": 1, "tid": 1,
             "args": {"trace": rid}},
            {"name": "decode", "cat": "request", "ph": "X",
             "ts": (t0 + self.SKEW + 0.3) * 1e6, "dur": 0.5e6, "pid": 1, "tid": 1,
             "args": {"trace": rid}},
        ]
        router._note_owner(rid, "a")

    def test_skew_corrected_single_timeline(self, fleet_router):
        a = FleetStub(skew_s=self.SKEW)
        router, port, _ = fleet_router([("a", a)])
        router.pool.poll_once()  # health probes estimate the clock offset
        est = router.pool.clock_offset("a")
        assert est == pytest.approx(self.SKEW, abs=0.25)
        self._seed_two_tier_trace(router, a, "rtr-0")
        status, doc = get_json(port, "/debug/trace?trace=rtr-0")
        assert status == 200
        assert doc["otherData"]["trace"] == "rtr-0"
        assert doc["otherData"]["replica"] == "a"
        evs = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
        assert set(evs) == {"router_request", "queue", "decode"}
        # distinct pid lanes per tier
        assert evs["router_request"]["pid"] != evs["queue"]["pid"]
        # corrected timestamps: replica spans land INSIDE the router span and
        # keep their order (monotonic corrected timeline)
        r = evs["router_request"]
        slack = 0.25e6  # offset-estimate error budget (us)
        for name in ("queue", "decode"):
            assert r["ts"] - slack <= evs[name]["ts"], name
            assert (evs[name]["ts"] + evs[name]["dur"]
                    <= r["ts"] + r["dur"] + slack), name
        assert evs["queue"]["ts"] < evs["decode"]["ts"]

    def test_unknown_owner_falls_back_to_router_only(self, fleet_router):
        a = FleetStub()
        router, port, _ = fleet_router([("a", a)])
        router.tracer.add_span("router_request", router.tracer.now(), 0.1,
                               trace="rtr-99")
        status, doc = get_json(port, "/debug/trace?trace=rtr-99")
        assert status == 200
        names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
        assert names == {"router_request"}
        assert doc["otherData"]["replica"] is None

    def test_dropped_counts_ride_along(self, fleet_router):
        a = FleetStub()
        router, port, _ = fleet_router([("a", a)])
        self._seed_two_tier_trace(router, a, "rtr-1")
        _, doc = get_json(port, "/debug/trace?trace=rtr-1")
        assert set(doc["otherData"]["dropped_spans"]) == {"router", "a"}

    def test_since_ts_cursor_stays_incremental_ring_read(self, fleet_router):
        # a since_ts cursor is the incremental-scrape contract: it must read
        # the router's own ring (honoring the cursor), not trigger a stitch
        a = FleetStub()
        router, port, _ = fleet_router([("a", a)])
        self._seed_two_tier_trace(router, a, "rtr-2")
        cursor = router.tracer.now()
        status, doc = get_json(port, f"/debug/trace?trace=rtr-2&since_ts={cursor}")
        assert status == 200
        assert "trace" not in doc["otherData"]  # not the stitched shape
        assert [e for e in doc["traceEvents"] if e.get("ph") == "X"] == []
        # and everything before the cursor is still there without it filtered
        status, doc = get_json(port, f"/debug/trace?trace=rtr-2&since_ts=0")
        names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
        assert names == {"router_request"}  # router ring only, no replica fetch


# ---------------------------------------------------------------- end-to-end
@pytest.fixture(scope="module")
def model():
    from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine_factory(model):
    from paddlenlp_tpu.experimental import InferenceEngine

    def make_engine():
        return InferenceEngine(model, max_batch_size=4, block_size=4,
                               num_blocks=128, max_blocks_per_seq=32,
                               decode_steps=4)
    return make_engine


def post_completion(port, payload, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions", body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


class TestTwoTierEndToEnd:
    """ISSUE 6 acceptance: a two-replica fleet run yields ONE stitched Chrome
    trace for a request — router route/forward spans and replica
    queue/prefill/decode spans under one trace id with monotonic corrected
    timestamps — and head-based sampling keeps only deterministically-chosen
    traces on the replicas while sampled ones keep full detail."""

    def test_stitched_trace_single_request(self, model):
        from paddlenlp_tpu.serving.router import launch_fleet

        TRACER.clear()
        fleet = launch_fleet(2, make_engine_factory(model), poll_interval_s=0.2)
        try:
            status, body = post_completion(
                fleet.router_port, {"prompt": [5, 6, 7, 8], "max_tokens": 4})
            assert status == 200
            rid = body["id"]
            assert rid.startswith("rtr-")
            # retrospective per-request spans land at finish; one poll of slack
            deadline = time.time() + 5
            while time.time() < deadline and not TRACER.snapshot(trace=rid):
                time.sleep(0.02)
            status, doc = get_json(fleet.router_port, f"/debug/trace?trace={rid}")
            assert status == 200
            assert doc["otherData"]["trace"] == rid
            xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
            by_name = {}
            for e in xs:
                assert e["args"]["trace"] == rid  # one trace id end to end
                by_name.setdefault(e["name"], []).append(e)
            # router tier spans + replica tier spans in one document
            for name in ("route", "router_request", "queue", "prefill", "decode"):
                assert name in by_name, (name, sorted(by_name))
            router_pid = by_name["router_request"][0]["pid"]
            assert by_name["decode"][0]["pid"] != router_pid  # distinct lanes
            # monotonic corrected timeline: queue -> prefill -> decode inside
            # the router's request span (same host, offset ~0, 0.5s slack)
            rq = by_name["router_request"][0]
            q, p, d = (by_name[n][0] for n in ("queue", "prefill", "decode"))
            assert q["ts"] <= p["ts"] <= d["ts"]
            slack = 0.5e6
            for ev in (q, p, d):
                assert rq["ts"] - slack <= ev["ts"] <= rq["ts"] + rq["dur"] + slack
            # device correlation: engine phase spans carry the step id that
            # StepTraceAnnotation stamps on the device timeline
            engine_spans = [s for s in TRACER.snapshot()
                            if s.cat == "engine" and s.args and "step" in s.args]
            assert engine_spans and all(s.args["step"] >= 0 for s in engine_spans)
        finally:
            fleet.shutdown(drain_timeout_s=10)
            TRACER.clear()

    def test_head_sampling_thins_replica_spans(self, model):
        from paddlenlp_tpu.serving.router import launch_fleet

        TRACER.clear()
        n_requests, every = 24, 8
        fleet = launch_fleet(2, make_engine_factory(model), poll_interval_s=0.2,
                             trace_sample_every=every)
        try:
            rids = []
            for _ in range(n_requests):
                status, body = post_completion(
                    fleet.router_port, {"prompt": [5, 6, 7], "max_tokens": 2})
                assert status == 200
                rids.append(body["id"])
            want_sampled = {r for r in rids if trace_sampled(r, every)}
            assert 0 < len(want_sampled) < n_requests / 4
            deadline = time.time() + 5
            while time.time() < deadline:
                got = {s.trace for s in TRACER.snapshot()
                       if s.trace in set(rids)}
                if got == want_sampled:
                    break
                time.sleep(0.05)
            # the replicas recorded EXACTLY the router's deterministic 1-in-N
            # choice: unsampled requests took the no-op path...
            assert got == want_sampled
            # ...while sampled ones kept full per-request detail
            for rid in want_sampled:
                names = {s.name for s in TRACER.snapshot(trace=rid)}
                assert {"queue", "prefill", "decode"} <= names, (rid, names)
        finally:
            fleet.shutdown(drain_timeout_s=10)
            # drop the rtr-N sampling marks pinned on the process-global
            # tracer: later tests mint fresh routers whose ids restart at
            # rtr-0 and must not inherit this fleet's decisions
            TRACER.clear()
