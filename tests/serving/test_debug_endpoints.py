"""Debug introspection endpoints (ISSUE 2 acceptance): a served request must
produce a span timeline retrievable via /debug/trace that parses as valid
Chrome trace-event JSON, and /debug/requests must show in-flight and finished
request timelines."""

import http.client
import json
import time

import pytest

from paddlenlp_tpu.experimental import InferenceEngine
from paddlenlp_tpu.serving import MetricsRegistry, SchedulerConfig, ServingServer
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def server_port():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    model = LlamaForCausalLM.from_config(cfg, seed=0)
    engine = InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=256,
                             max_blocks_per_seq=32, decode_steps=4)
    server = ServingServer(engine, registry=MetricsRegistry(),
                           scheduler_config=SchedulerConfig(max_inflight=8))
    port = server.start_in_thread()
    yield server, port
    server.shutdown(drain_timeout_s=10)


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, body


def _complete(port, max_tokens=8):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/completions",
                 body=json.dumps({"prompt": [5, 6, 7], "max_tokens": max_tokens}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = json.loads(resp.read())
    conn.close()
    assert resp.status == 200
    return out


class TestDebugTrace:
    def test_request_produces_valid_chrome_trace(self, server_port):
        server, port = server_port
        _complete(port)
        status, body = _get(port, "/debug/trace")
        assert status == 200
        parsed = json.loads(body)  # valid JSON is the acceptance bar
        events = parsed["traceEvents"]
        names = {e["name"] for e in events}
        # request lifecycle spans (engine loop) + engine phase spans
        assert {"request", "prefill", "decode", "admission"} <= names
        for e in events:
            assert {"name", "ph", "pid", "tid"} <= set(e)
            if e["ph"] == "X":
                assert "ts" in e and e["dur"] >= 0
        # the request's phases share one trace id
        req_ev = next(e for e in events if e["name"] == "request")
        trace_id = req_ev["args"]["trace"]
        phases = {e["name"] for e in events
                  if e.get("args", {}).get("trace") == trace_id}
        assert {"queue", "prefill", "decode", "request"} <= phases

    def test_trace_grows_with_requests(self, server_port):
        server, port = server_port
        _, before = _get(port, "/debug/trace")
        _complete(port)
        _, after = _get(port, "/debug/trace")
        assert len(json.loads(after)["traceEvents"]) > len(json.loads(before)["traceEvents"])


class TestDebugTraceFilter:
    """`/debug/trace?trace=req-N` must dump ONE request's timeline without
    shipping the whole ring, and `since_ts` must work as an incremental
    scrape cursor."""

    def test_trace_filter_isolates_one_request(self, server_port):
        server, port = server_port
        _complete(port)
        _complete(port)
        status, body = _get(port, "/debug/requests")
        trace_id = json.loads(body)["recent"][-1]["trace"]
        status, body = _get(port, f"/debug/trace?trace={trace_id}")
        assert status == 200
        events = json.loads(body)["traceEvents"]
        data_events = [e for e in events if e["ph"] != "M"]
        assert data_events, "filtered dump is empty"
        # every non-metadata event belongs to the requested trace ...
        assert all(e.get("args", {}).get("trace") == trace_id for e in data_events)
        # ... and the full request phase timeline is present
        assert {"queue", "prefill", "decode", "request"} <= {e["name"] for e in data_events}
        # an unknown trace id filters down to nothing (not an error)
        status, body = _get(port, "/debug/trace?trace=req-does-not-exist")
        assert status == 200
        assert [e for e in json.loads(body)["traceEvents"] if e["ph"] != "M"] == []

    def test_spans_endpoint_accepts_same_filter(self, server_port):
        server, port = server_port
        _complete(port)
        status, body = _get(port, "/debug/requests")
        trace_id = json.loads(body)["recent"][-1]["trace"]
        status, body = _get(port, f"/debug/spans?trace={trace_id}")
        assert status == 200
        spans = [json.loads(line) for line in body.decode().splitlines() if line]
        assert spans and all(s.get("trace") == trace_id for s in spans)

    def test_since_ts_cursor(self, server_port):
        server, port = server_port
        _complete(port)
        cursor = server.tracer.now()
        # nothing recorded after the cursor yet
        status, body = _get(port, f"/debug/spans?since_ts={cursor}")
        old = [json.loads(line) for line in body.decode().splitlines() if line]
        _complete(port)
        status, body = _get(port, f"/debug/spans?since_ts={cursor}")
        new = [json.loads(line) for line in body.decode().splitlines() if line]
        assert len(new) > len(old)
        assert all(s["ts"] >= cursor for s in new)

    def test_bad_since_ts_is_a_clean_400(self, server_port):
        server, port = server_port
        status, body = _get(port, "/debug/trace?since_ts=banana")
        assert status == 400
        assert "since_ts" in json.loads(body)["error"]


class TestDebugRequests:
    def test_finished_request_in_recent(self, server_port):
        server, port = server_port
        out = _complete(port)
        status, body = _get(port, "/debug/requests")
        assert status == 200
        payload = json.loads(body)
        assert {"inflight", "recent"} <= set(payload)
        assert payload["recent"], "finished request missing from /debug/requests"
        rec = payload["recent"][-1]
        assert rec["state"] == "finished"
        assert rec["finish_reason"] == out["choices"][0]["finish_reason"]
        assert rec["trace"].startswith("req-")
        assert rec["output_tokens"] >= 1 and rec["ttft_s"] >= 0

    def test_inflight_request_visible(self, server_port):
        server, port = server_port
        # long request submitted straight through the scheduler (no HTTP block);
        # 100 new tokens ≈ hundreds of ms on CPU — plenty of polls catch it
        from paddlenlp_tpu.experimental import SamplingParams

        handle = server.scheduler.submit(
            [5, 6, 7, 8], SamplingParams(max_new_tokens=100), timeout_s=60)
        try:
            deadline = time.time() + 30
            seen = None
            while time.time() < deadline and not handle.done():
                _, body = _get(port, "/debug/requests")
                inflight = json.loads(body)["inflight"]
                if inflight:
                    seen = inflight[0]
                    break
                time.sleep(0.005)
            assert seen is not None, "request never appeared in /debug/requests"
            assert seen["trace"] == handle.trace
            assert seen["state"] in ("submitted", "queued", "prefill", "decode")
            assert seen["age_s"] >= 0
        finally:
            server.scheduler.cancel(handle)
            handle.result(timeout=30)
