"""``GET /debug/efficiency`` + goodput federation round-trip (ISSUE 15).

The replica serves its engine's goodput ledger; the router folds every
replica's doc plus per-replica goodput into ``/fleet/slo`` and federates the
new counter families through ``/fleet/metrics``; the training exporter
answers the same route with its compile counters. Also covers the
``priority`` label satellite on ``requests_total``/``requests_shed_total``."""

import http.client
import json

import pytest

from paddlenlp_tpu.observability import parse_prometheus_text
from paddlenlp_tpu.observability.exporter import ObservabilityExporter
from paddlenlp_tpu.serving import MetricsRegistry, SchedulerConfig, ServingServer
from paddlenlp_tpu.serving.metrics import MetricsRegistry as _MR  # noqa: F401


@pytest.fixture(scope="module")
def model():
    from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine_factory(model):
    from paddlenlp_tpu.experimental import InferenceEngine

    def make_engine():
        return InferenceEngine(model, max_batch_size=4, block_size=4,
                               num_blocks=128, max_blocks_per_seq=32,
                               decode_steps=4)
    return make_engine


def get_json(port, path, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def get_text(port, path, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def post_completion(port, payload, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions", body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


class TestReplicaEndpoint:
    @pytest.fixture(scope="class")
    def server(self, model):
        srv = ServingServer(make_engine_factory(model)(),
                            registry=MetricsRegistry(),
                            scheduler_config=SchedulerConfig(max_inflight=8))
        port = srv.start_in_thread()
        yield srv, port
        srv.shutdown(drain_timeout_s=5)

    def test_efficiency_doc_after_traffic(self, server):
        srv, port = server
        status, _ = post_completion(
            port, {"prompt": [5, 6, 7], "max_tokens": 4, "priority": "batch"})
        assert status == 200
        status, doc = get_json(port, "/debug/efficiency")
        assert status == 200
        assert doc["tier"] == "serving" and doc["engine_state"] == "running"
        totals = doc["ledger"]["totals"]
        assert totals["fed"] == sum(
            (totals[k] for k in ("useful", "padding", "spec_rejected", "rework")))
        assert totals["useful"] > 0
        assert doc["mfu"] is None  # CPU run must not fake an MFU
        assert 0.0 < doc["goodput_ratio"] <= 1.0
        assert doc["step_anatomy"]["window_steps"] >= 1
        assert doc["ledger"]["compiles"].get("prefill", 0) >= 1
        json.dumps(doc)  # strictly serializable end to end

    def test_metrics_carry_ledger_and_priority_labels(self, server):
        srv, port = server
        status, text = get_text(port, "/metrics")
        assert status == 200
        fams = parse_prometheus_text(text)
        fed = fams["paddlenlp_serving_fed_tokens_total"].value()
        useful = fams["paddlenlp_serving_useful_tokens_total"].value()
        assert fed > 0 and 0 < useful <= fed
        waste = sum(
            v for (_s, labels), v in
            fams["paddlenlp_serving_wasted_tokens_total"].samples.items())
        assert fed == useful + waste  # conservation survives the metrics hop
        assert fams["paddlenlp_serving_goodput_ratio"].value() == \
            pytest.approx(useful / fed)
        # the batch-priority request is visible per class (PR-14 brownout
        # ladder observability satellite)
        assert fams["paddlenlp_serving_requests_total"].value(
            status="length", priority="batch", tenant="default") >= 1
        assert "paddlenlp_serving_step_gap_seconds_bucket" in text
        assert "paddlenlp_serving_jit_shape_buckets" in text

    def test_shed_counter_labeled_by_priority(self, server):
        srv, port = server
        srv.scheduler.brownout.push(1, reason="slo_fast_burn", ttl_s=30.0)
        try:
            status, body = post_completion(
                port, {"prompt": [5, 6, 7], "max_tokens": 4,
                       "priority": "best_effort"})
            assert status == 503
            assert body["error"]["type"] == "overloaded_shed"
            assert srv.loop.metrics.shed.value(
                reason="shed", priority="best_effort", tenant="default") >= 1
        finally:
            srv.scheduler.brownout.push(0, reason="slo_fast_burn")


class TestFleetRoundTrip:
    @pytest.fixture(scope="class")
    def fleet(self, model):
        from paddlenlp_tpu.serving.router import launch_fleet

        fleet = launch_fleet(2, make_engine_factory(model), poll_interval_s=0.2)
        for i in range(6):
            status, _ = post_completion(
                fleet.router_port,
                {"prompt": [30 + i, 6, 7], "max_tokens": 4})
            assert status == 200
        yield fleet
        fleet.shutdown(drain_timeout_s=5)

    def test_router_folds_replica_docs(self, fleet):
        status, doc = get_json(fleet.router_port, "/debug/efficiency")
        assert status == 200
        assert doc["tier"] == "router" and doc["skipped"] == []
        assert len(doc["replicas"]) == 2
        fed = useful = 0
        for rid, rdoc in doc["replicas"].items():
            totals = rdoc["ledger"]["totals"]
            assert totals["fed"] >= totals["useful"]
            fed += totals["fed"]
            useful += totals["useful"]
        assert doc["fleet"]["fed_tokens"] == fed
        assert doc["fleet"]["useful_tokens"] == useful
        assert doc["fleet"]["goodput_ratio"] == pytest.approx(
            useful / fed) if fed else True

    def test_fleet_slo_carries_goodput_fold(self, fleet):
        status, doc = get_json(fleet.router_port, "/fleet/slo")
        assert status == 200
        gp = doc["goodput"]
        assert set(gp["replicas"]) == set(doc["replicas"])
        for rdoc in gp["replicas"].values():
            assert 0.0 < rdoc["goodput_ratio"] <= 1.0
        assert gp["fleet"]["fed_tokens"] == sum(
            r["fed_tokens"] for r in gp["replicas"].values())
        assert "padding" in gp["fleet"]["wasted_tokens"]

    def test_fleet_metrics_federate_ledger_series(self, fleet):
        status, text = get_text(fleet.router_port, "/fleet/metrics")
        assert status == 200
        fams = parse_prometheus_text(text)
        fed_fam = fams["paddlenlp_serving_fed_tokens_total"]
        replicas = {dict(labels)["replica"]
                    for (_s, labels), _v in fed_fam.samples.items()}
        assert len(replicas) == 2  # one series per replica, re-labeled


class TestTrainingExporter:
    def test_exporter_answers_efficiency(self):
        registry = MetricsRegistry()
        registry.counter("jax_jit_compile_total", "compiles").inc(3)
        exp = ObservabilityExporter(registry=registry)
        port = exp.start()
        try:
            status, doc = get_json(port, "/debug/efficiency")
            assert status == 200
            assert doc["tier"] == "training" and doc["ledger"] is None
            assert doc["compiles"] == 3
        finally:
            exp.shutdown()

    def test_exporter_efficiency_fn_override(self):
        exp = ObservabilityExporter(registry=MetricsRegistry(),
                                    efficiency_fn=lambda: {"tier": "custom", "x": 1})
        port = exp.start()
        try:
            status, doc = get_json(port, "/debug/efficiency")
            assert status == 200 and doc == {"tier": "custom", "x": 1}
        finally:
            exp.shutdown()
