"""Metrics plane unit tests: counter/gauge/histogram semantics + Prometheus
text exposition (no jax, no engine — the module must stand alone)."""

import threading

import pytest

from paddlenlp_tpu.serving.metrics import Counter, Gauge, Histogram, MetricsRegistry


class TestCounter:
    def test_inc_and_labels(self):
        c = Counter("req_total", "requests", labelnames=("status",))
        c.inc(status="ok")
        c.inc(2, status="ok")
        c.inc(status="err")
        assert c.value(status="ok") == 3 and c.value(status="err") == 1

    def test_monotonic(self):
        c = Counter("x", "")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_label_mismatch_rejected(self):
        c = Counter("y", "", labelnames=("a",))
        with pytest.raises(ValueError):
            c.inc(b="nope")

    def test_thread_safety(self):
        c = Counter("z", "")
        threads = [threading.Thread(target=lambda: [c.inc() for _ in range(1000)])
                   for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == 8000


class TestGauge:
    def test_set_inc_dec(self):
        g = Gauge("depth", "")
        g.set(5)
        g.inc()
        g.dec(2)
        assert g.value() == 4

    def test_pull_mode(self):
        state = {"v": 0}
        g = Gauge("pull", "")
        g.set_function(lambda: state["v"])
        state["v"] = 7
        assert g.value() == 7
        assert "pull 7" in "\n".join(g.expose())


class TestHistogram:
    def test_buckets_sum_count(self):
        h = Histogram("lat", "", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count() == 4 and h.sum() == pytest.approx(55.55)
        lines = "\n".join(h.expose())
        assert 'lat_bucket{le="0.1"} 1' in lines
        assert 'lat_bucket{le="1"} 2' in lines
        assert 'lat_bucket{le="10"} 3' in lines
        assert 'lat_bucket{le="+Inf"} 4' in lines
        assert "lat_count 4" in lines

    def test_percentile_bucket_upper_bound(self):
        h = Histogram("p", "", buckets=(1, 2, 4, 8))
        for v in (0.5, 1.5, 1.6, 3.0):
            h.observe(v)
        assert h.percentile(0.5) == 2
        assert h.percentile(0.99) == 4
        assert Histogram("empty", "").percentile(0.5) == 0.0


class TestRegistry:
    def test_idempotent_and_exposition(self):
        r = MetricsRegistry()
        c1 = r.counter("a_total", "help text")
        c2 = r.counter("a_total")
        assert c1 is c2
        c1.inc(3)
        r.gauge("b").set(1.5)
        text = r.expose()
        assert "# HELP a_total help text" in text
        assert "# TYPE a_total counter" in text
        assert "a_total 3" in text
        assert "b 1.5" in text
        assert text.endswith("\n")

    def test_kind_conflict(self):
        r = MetricsRegistry()
        r.counter("m")
        with pytest.raises(ValueError):
            r.gauge("m")

    def test_unregistered_zero_series(self):
        r = MetricsRegistry()
        r.counter("never_touched_total", "")
        assert "never_touched_total 0" in r.expose()
