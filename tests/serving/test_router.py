"""Router front tier unit tests (ISSUE 4): routing-policy ordering,
prefix-affinity determinism, the health-poller state machine, and the proxy's
reroute/failover behaviors against scriptable stub replicas — no engine, no
jax compute, so the whole file runs in milliseconds."""

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from paddlenlp_tpu.serving.metrics import MetricsRegistry
from paddlenlp_tpu.serving.router import (
    DEGRADED,
    DOWN,
    HEALTHY,
    RECOVERING,
    HashRing,
    LeastLoadedPolicy,
    PrefixAffinityPolicy,
    ProbeResult,
    ReplicaPool,
    ReplicaSnapshot,
    RouterMetrics,
    RouterServer,
    load_score,
    resolve_policy,
)
from paddlenlp_tpu.utils.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def snap(rid, state=HEALTHY, inflight=0, queue=0, kv=0.0):
    return ReplicaSnapshot(id=rid, host="127.0.0.1", port=0, state=state,
                           inflight=inflight, queue_depth=queue, kv_utilization=kv,
                           retry_after_s=None, consecutive_failures=0, last_poll_t=None)


# --------------------------------------------------------------------- policy
class TestLeastLoaded:
    def test_load_score_components(self):
        assert load_score(snap("a", inflight=2, queue=3, kv=0.5)) == 5.5

    def test_orders_by_effective_load(self):
        snaps = [snap("a", inflight=4), snap("b", inflight=1, queue=1),
                 snap("c", kv=0.9)]
        order = [s.id for s in LeastLoadedPolicy().select(snaps)]
        assert order == ["c", "b", "a"]  # 0.9 < 2 < 4

    def test_down_excluded_degraded_last(self):
        snaps = [snap("a", state=DOWN), snap("b", state=DEGRADED),
                 snap("c", inflight=50), snap("d", state=RECOVERING)]
        order = [s.id for s in LeastLoadedPolicy().select(snaps)]
        assert order == ["c", "d", "b"]  # loaded-healthy > recovering > degraded; DOWN gone

    def test_exclude_set(self):
        snaps = [snap("a"), snap("b")]
        order = [s.id for s in LeastLoadedPolicy().select(snaps, exclude=frozenset({"a"}))]
        assert order == ["b"]

    def test_deterministic_tiebreak(self):
        snaps = [snap("b"), snap("a")]
        assert [s.id for s in LeastLoadedPolicy().select(snaps)] == ["a", "b"]


class TestPrefixAffinity:
    IDS = ["r0", "r1", "r2", "r3"]

    def snaps(self, **states):
        return [snap(i, state=states.get(i, HEALTHY)) for i in self.IDS]

    def test_same_prefix_same_replica_across_instances(self):
        p1 = PrefixAffinityPolicy(prefix_tokens=4)
        p2 = PrefixAffinityPolicy(prefix_tokens=4)
        prompt = [5, 6, 7, 8, 99, 100]
        a = [s.id for s in p1.select(self.snaps(), prompt=prompt)]
        b = [s.id for s in p2.select(self.snaps(), prompt=prompt)]
        assert a == b  # no hidden state: a fresh policy agrees

    def test_prefix_key_ignores_tail(self):
        p = PrefixAffinityPolicy(prefix_tokens=3)
        base = [5, 6, 7]
        pin = p.select(self.snaps(), prompt=base + [1000])[0].id
        for tail in ([1], [42, 43], list(range(50))):
            assert p.select(self.snaps(), prompt=base + tail)[0].id == pin

    def test_pin_ignores_load_below_spill_threshold(self):
        p = PrefixAffinityPolicy(prefix_tokens=3)
        prompt = [5, 6, 7, 8]
        pin = p.select(self.snaps(), prompt=prompt)[0].id
        # warm-but-under-threshold pin holds: affinity beats mild imbalance
        loaded = [snap(i, inflight=5 if i == pin else 0) for i in self.IDS]
        assert p.select(loaded, prompt=prompt)[0].id == pin
        # spilling disabled: the pin holds no matter how hot it runs
        p_off = PrefixAffinityPolicy(prefix_tokens=3, spill_load_score=None)
        melted = [snap(i, inflight=30 if i == pin else 0) for i in self.IDS]
        assert p_off.select(melted, prompt=prompt)[0].id == pin

    def test_distribution_covers_all_replicas(self):
        p = PrefixAffinityPolicy(prefix_tokens=2)
        hits = {i: 0 for i in self.IDS}
        for k in range(200):
            hits[p.select(self.snaps(), prompt=[k, k + 1, 7])[0].id] += 1
        assert all(v > 0 for v in hits.values()), hits

    def test_down_pin_falls_to_agreed_successor(self):
        p = PrefixAffinityPolicy(prefix_tokens=3)
        prompt = [5, 6, 7, 8]
        order = [s.id for s in p.select(self.snaps(), prompt=prompt)]
        pin, successor = order[0], order[1]
        # pinned replica DOWN: everyone agrees on the same next ring member
        failed = p.select(self.snaps(**{pin: DOWN}), prompt=prompt)
        assert failed[0].id == successor
        # DEGRADED pin yields too (state rank outranks ring order) ...
        degraded = p.select(self.snaps(**{pin: DEGRADED}), prompt=prompt)
        assert degraded[0].id == successor
        # ... but stays a candidate of last resort
        assert pin in [s.id for s in degraded]

    def test_membership_change_moves_few_prefixes(self):
        """Consistent hashing: adding a 5th replica should re-pin roughly 1/5
        of the prefix space, not most of it."""
        four = self.snaps()
        five = four + [snap("r4")]
        p = PrefixAffinityPolicy(prefix_tokens=2)
        moved = sum(
            1 for k in range(300)
            if p.select(four, prompt=[k, 3, 9])[0].id != p.select(five, prompt=[k, 3, 9])[0].id)
        assert moved / 300 < 0.5, f"{moved}/300 prefixes re-pinned"

    def test_string_prompt_and_fallback(self):
        p = PrefixAffinityPolicy(prefix_tokens=4)
        a = p.select(self.snaps(), prompt="You are a helpful assistant. Task A")[0].id
        b = p.select(self.snaps(), prompt="You are a helpful assistant. Task B")[0].id
        assert a == b  # shared 16-char prefix window pins together
        # no prompt at all: least-loaded fallback
        loaded = [snap("r0", inflight=9), snap("r1")]
        assert p.select(loaded, prompt=None)[0].id == "r1"

    def test_ring_walk_is_total(self):
        ring = HashRing(self.IDS, vnodes=16)
        order = ring.ordered("some-prefix")
        assert sorted(order) == sorted(self.IDS)

    def test_resolve_policy(self):
        assert isinstance(resolve_policy("least_loaded"), LeastLoadedPolicy)
        assert isinstance(resolve_policy("prefix_affinity"), PrefixAffinityPolicy)
        with pytest.raises(ValueError):
            resolve_policy("round_robin")


class TestWeightedSpill:
    """Satellite contract: a too-hot pinned replica spills its prefix to the
    agreed ring successor instead of hot-spotting — without scattering the
    prefix or trading cache warmth for a degraded replica."""

    IDS = ["r0", "r1", "r2", "r3"]

    def ring_order(self, prompt):
        p = PrefixAffinityPolicy(prefix_tokens=3, spill_load_score=None)
        return [s.id for s in p.select(
            [snap(i) for i in self.IDS], prompt=prompt)]

    def test_hot_pin_spills_to_agreed_ring_successor(self):
        prompt = [5, 6, 7, 8]
        pin, successor = self.ring_order(prompt)[:2]
        p = PrefixAffinityPolicy(prefix_tokens=3, spill_load_score=8.0)
        hot = [snap(i, inflight=20 if i == pin else 0) for i in self.IDS]
        got = [s.id for s in p.select(hot, prompt=prompt)]
        assert got[0] == successor
        # the rest of the walk keeps ring order: every client of the prefix
        # spills to the SAME replica (co-located on two, not scattered)
        fresh = PrefixAffinityPolicy(prefix_tokens=3, spill_load_score=8.0)
        assert [s.id for s in fresh.select(hot, prompt=prompt)] == got

    def test_spill_skips_hot_successor_for_next_cool_candidate(self):
        prompt = [5, 6, 7, 8]
        order = self.ring_order(prompt)
        pin, successor, third = order[0], order[1], order[2]
        p = PrefixAffinityPolicy(prefix_tokens=3, spill_load_score=8.0)
        loads = {pin: 20, successor: 15}
        hot = [snap(i, inflight=loads.get(i, 0)) for i in self.IDS]
        assert p.select(hot, prompt=prompt)[0].id == third

    def test_uniformly_hot_fleet_keeps_pin(self):
        """When every candidate is past the threshold the pin stands —
        bouncing between equally-loaded replicas only sheds cache warmth."""
        prompt = [5, 6, 7, 8]
        pin = self.ring_order(prompt)[0]
        p = PrefixAffinityPolicy(prefix_tokens=3, spill_load_score=8.0)
        hot = [snap(i, inflight=20) for i in self.IDS]
        assert p.select(hot, prompt=prompt)[0].id == pin

    def test_never_spills_onto_worse_state_replica(self):
        prompt = [5, 6, 7, 8]
        pin = self.ring_order(prompt)[0]
        p = PrefixAffinityPolicy(prefix_tokens=3, spill_load_score=8.0)
        snaps = [snap(i, inflight=20 if i == pin else 0,
                      state=HEALTHY if i == pin else DEGRADED)
                 for i in self.IDS]
        # every same-state alternative is missing: the hot pin stands rather
        # than trading cache warmth for a DEGRADED replica
        assert p.select(snaps, prompt=prompt)[0].id == pin

    def test_spill_threshold_validation(self):
        with pytest.raises(ValueError):
            PrefixAffinityPolicy(spill_load_score=0.0)
        with pytest.raises(ValueError):
            PrefixAffinityPolicy(spill_load_score=-1.0)


# --------------------------------------------------------------------- pool
class TestPoolStateMachine:
    def make_pool(self, results, **kw):
        """Pool over one replica whose probes are scripted by ``results``
        (a list of ProbeResult | Exception)."""
        pool = ReplicaPool(metrics=RouterMetrics(MetricsRegistry()),
                           down_after=kw.pop("down_after", 2),
                           recovery_polls=kw.pop("recovery_polls", 2), **kw)
        replica = pool.add("127.0.0.1", 1, "r0")
        seq = iter(results)

        def fake_probe(_replica):
            item = next(seq)
            if isinstance(item, Exception):
                raise item
            return item

        pool._probe = fake_probe
        return pool, replica

    OK = ProbeResult(reachable=True, status="ok", inflight=3, queue_depth=2,
                     kv_utilization=0.5)
    SHED = ProbeResult(reachable=True, status="degraded", retry_after_s=4.0)

    def test_healthy_updates_load_fields(self):
        pool, r = self.make_pool([self.OK])
        pool.poll_once()
        s = pool.snapshots()[0]
        assert s.state == HEALTHY and s.inflight == 3 and s.queue_depth == 2
        assert s.kv_utilization == 0.5
        assert pool.metrics.replica_healthy.value(replica="r0") == 1.0

    def test_degraded_on_503(self):
        pool, r = self.make_pool([self.OK, self.SHED])
        pool.poll_once()
        pool.poll_once()
        s = pool.snapshots()[0]
        assert s.state == DEGRADED and s.retry_after_s == 4.0
        assert pool.metrics.replica_healthy.value(replica="r0") == 0.0
        assert pool.retry_after_hint() == 4.0

    def test_unreachable_degrades_then_down(self):
        pool, r = self.make_pool([self.OK, ConnectionRefusedError("boom"),
                                  ConnectionRefusedError("boom")])
        pool.poll_once()
        pool.poll_once()
        assert pool.snapshots()[0].state == DEGRADED  # first failure: benefit of the doubt
        pool.poll_once()
        assert pool.snapshots()[0].state == DOWN  # down_after=2 consecutive

    def test_recovery_is_probational(self):
        pool, r = self.make_pool([ConnectionRefusedError(), ConnectionRefusedError(),
                                  self.OK, self.OK])
        pool.poll_once(), pool.poll_once()
        assert pool.snapshots()[0].state == DOWN
        pool.poll_once()
        assert pool.snapshots()[0].state == RECOVERING  # first clean probe
        assert pool.metrics.replica_healthy.value(replica="r0") == 0.0
        pool.poll_once()
        assert pool.snapshots()[0].state == HEALTHY  # recovery_polls=2 reached
        assert pool.metrics.replica_healthy.value(replica="r0") == 1.0

    def test_relapse_during_recovery_resets_streak(self):
        pool, r = self.make_pool([ConnectionRefusedError(), ConnectionRefusedError(),
                                  self.OK, ConnectionRefusedError(), self.OK, self.OK])
        for _ in range(3):
            pool.poll_once()
        assert pool.snapshots()[0].state == RECOVERING
        pool.poll_once()  # relapse
        assert pool.snapshots()[0].state == DEGRADED
        pool.poll_once()
        assert pool.snapshots()[0].state == HEALTHY  # was never DOWN again: direct promote

    def test_forward_feedback_demotes_immediately(self):
        pool, r = self.make_pool([])
        assert pool.snapshots()[0].state == HEALTHY
        pool.note_forward_failure("r0")
        assert pool.snapshots()[0].state == DEGRADED
        pool.note_degraded("r0", retry_after_s=2.5)
        s = pool.snapshots()[0]
        assert s.state == DEGRADED and s.retry_after_s == 2.5

    def test_health_poll_fault_point(self):
        """router.health_poll armed: the probe raises like a transport error
        and drives the demotion deterministically."""
        pool = ReplicaPool(metrics=RouterMetrics(MetricsRegistry()), down_after=1)
        pool.add("127.0.0.1", 1, "r0")  # nothing listens; probe would fail anyway
        FAULTS.arm("router.health_poll", nth=1)
        pool.poll_once()
        assert FAULTS.fired("router.health_poll") == 1
        assert pool.snapshots()[0].state == DOWN  # down_after=1


# --------------------------------------------------------------------- proxy
class StubReplica:
    """Scriptable replica speaking just enough of the ServingServer surface:
    /health, /metrics, /v1/completions (SSE + batch), /v1/abort.

    ``mode`` picks the completion script:
      ok               stream/batch the configured tokens, finish "length"
      reject429        429 window-full
      reject503        503 + Retry-After (engine recovering)
      engine_error_pre SSE terminal engine_error before any token
      engine_error_mid 2 tokens, then terminal engine_error
      die_midstream    2 tokens, then drop the connection (no [DONE])
    """

    def __init__(self, mode="ok", tokens=(7, 8, 9), health="ok", kv=0.25,
                 token_delay_s=0.0):
        self.mode = mode
        self.tokens = list(tokens)
        self.health = health
        self.kv = kv
        self.token_delay_s = token_delay_s
        self.requests = []  # /v1/completions payloads received
        self.aborts = []  # /v1/abort payloads received
        self.drains = []  # /admin/drain payloads received (drain propagation)
        self._ids = iter(range(10_000))
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _json(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    for k, v in (headers or {}).items():
                        self.send_header(k, str(v))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    # the router tore this leg down on purpose (batch hedge
                    # loser): not an error worth a stack trace
                    pass

            def do_GET(self):
                if self.path == "/health":
                    code = 200 if stub.health == "ok" else 503
                    self._json(code, {"status": stub.health,
                                      "scheduler": {"inflight": len(stub.requests)},
                                      "engine": {"queue_depth": 0}})
                elif self.path == "/metrics":
                    text = ("# HELP paddlenlp_serving_kv_utilization x\n"
                            "# TYPE paddlenlp_serving_kv_utilization gauge\n"
                            f"paddlenlp_serving_kv_utilization {stub.kv}\n")
                    body = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json(404, {"error": "no route"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/v1/abort":
                    stub.aborts.append(payload)
                    self._json(200, {"id": payload.get("id"), "cancelled": True})
                    return
                if self.path == "/admin/drain":
                    # replica-side drain propagation (the real server flips
                    # its scheduler to draining here)
                    stub.drains.append(payload)
                    self._json(200, {"draining": True})
                    return
                stub.requests.append(payload)
                if "prompt" not in payload:  # mirror the real server's validation
                    self._json(400, {"error": {"message": "missing required field 'prompt'",
                                               "type": "invalid_request"}})
                    return
                cid = f"cmpl-{next(stub._ids)}"
                if stub.mode == "reject429":
                    self._json(429, {"error": {"message": "full", "type": "rate_limit_exceeded"}})
                    return
                if stub.mode == "reject503":
                    self._json(503, {"error": {"message": "recovering",
                                               "type": "engine_recovering"}},
                               headers={"Retry-After": 7})
                    return
                if stub.mode == "fail500":
                    self._json(500, {"error": {"message": "boom", "type": "internal_error"}})
                    return
                if payload.get("stream"):
                    self._stream(cid, payload)
                else:
                    self._batch(cid, payload)

            def _batch(self, cid, payload):
                if stub.mode in ("engine_error_pre", "engine_error_mid"):
                    self._json(200, {"id": cid, "object": "text_completion",
                                     "choices": [{"index": 0, "finish_reason": "engine_error",
                                                  "token_ids": []}]})
                    return
                toks = stub.tokens[: int(payload.get("max_tokens", 16))]
                if stub.token_delay_s:
                    # batch "generation time": the whole response arrives late
                    time.sleep(stub.token_delay_s * len(toks))
                self._json(200, {"id": cid, "object": "text_completion",
                                 "choices": [{"index": 0, "finish_reason": "length",
                                              "token_ids": toks}],
                                 "usage": {"prompt_tokens": len(payload.get("prompt", [])),
                                           "completion_tokens": len(toks)}})

            def _stream(self, cid, payload):
                try:
                    self._stream_inner(cid, payload)
                except (BrokenPipeError, ConnectionResetError):
                    # the router tore this leg down on purpose (hedge loser,
                    # drain eviction): not an error worth a stack trace
                    pass

            def _stream_inner(self, cid, payload):
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Connection", "close")
                self.end_headers()

                def chunk(obj):
                    self.wfile.write(f"data: {json.dumps(obj)}\n\n".encode())
                    self.wfile.flush()

                def token_chunks(toks):
                    for t in toks:
                        if stub.token_delay_s:
                            time.sleep(stub.token_delay_s)
                        chunk({"id": cid, "object": "text_completion.chunk",
                               "choices": [{"index": 0, "token": t, "finish_reason": None}]})

                if stub.mode == "engine_error_pre":
                    chunk({"id": cid, "object": "text_completion.chunk",
                           "choices": [{"index": 0, "finish_reason": "engine_error"}]})
                    self.wfile.write(b"data: [DONE]\n\n")
                    return
                if stub.mode == "engine_error_mid":
                    token_chunks(stub.tokens[:2])
                    chunk({"id": cid, "object": "text_completion.chunk",
                           "choices": [{"index": 0, "finish_reason": "engine_error"}]})
                    self.wfile.write(b"data: [DONE]\n\n")
                    return
                if stub.mode == "die_midstream":
                    token_chunks(stub.tokens[:2])
                    self.wfile.flush()
                    self.connection.close()  # crash, not completion
                    return
                toks = stub.tokens[: int(payload.get("max_tokens", 16))]
                token_chunks(toks)
                chunk({"id": cid, "object": "text_completion.chunk",
                       "choices": [{"index": 0, "finish_reason": "length"}],
                       "usage": {"prompt_tokens": len(payload.get("prompt", [])),
                                 "completion_tokens": len(toks)}})
                self.wfile.write(b"data: [DONE]\n\n")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._httpd.daemon_threads = True
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        self.port = self._httpd.server_address[1]

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def post_completion(port, payload, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions", body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        headers = dict(resp.getheaders())
        if payload.get("stream"):
            toks, finish, ids = [], None, set()
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                data = line[len(b"data: "):]
                if data == b"[DONE]":
                    break
                ev = json.loads(data)
                ids.add(ev.get("id"))
                c = ev["choices"][0]
                if c.get("finish_reason"):
                    finish = c["finish_reason"]
                    final = ev
                elif "token" in c:
                    toks.append(c["token"])
            return resp.status, {"tokens": toks, "finish": finish, "ids": ids,
                                 "final": locals().get("final")}, headers
        return resp.status, json.loads(resp.read() or b"{}"), headers
    finally:
        conn.close()


@pytest.fixture
def stub_router():
    """Factory: build a router over stub replicas; tears everything down."""
    created = []

    def build(stubs, **router_kw):
        registry = MetricsRegistry()
        router = RouterServer(
            [("127.0.0.1", s.port, rid) for rid, s in stubs],
            registry=registry, poll_interval_s=30.0,  # later polls driven manually
            **router_kw)
        port = router.start_in_thread()
        created.append((router, [s for _, s in stubs]))
        # wait out the poller's startup sweep: a request racing a half-done
        # sweep would see asymmetric kv_utilization and flip the tiebreak
        deadline = time.time() + 5
        while (time.time() < deadline
               and any(s.last_poll_t is None for s in router.pool.snapshots())):
            time.sleep(0.005)
        return router, port, registry

    yield build
    for router, stubs in created:
        router.shutdown()
        for s in stubs:
            s.stop()


class TestProxy:
    def test_reroute_on_429(self, stub_router):
        a, b = StubReplica(mode="reject429"), StubReplica()
        router, port, reg = stub_router([("a", a), ("b", b)])
        status, body, _ = post_completion(port, {"prompt": [1, 2, 3], "max_tokens": 3})
        assert status == 200
        assert body["replica"] == "b" and body["choices"][0]["token_ids"] == [7, 8, 9]
        assert body["id"].startswith("rtr-")
        assert reg.get("paddlenlp_router_rerouted_total").value() == 1
        assert reg.get("paddlenlp_router_requests_total").value(replica="b", outcome="ok") == 1
        assert len(a.requests) == 1 and len(b.requests) == 1

    def test_reroute_on_503_marks_degraded(self, stub_router):
        a, b = StubReplica(mode="reject503"), StubReplica()
        router, port, reg = stub_router([("a", a), ("b", b)])
        status, body, _ = post_completion(port, {"prompt": [1, 2, 3], "max_tokens": 3})
        assert status == 200 and body["replica"] == "b"
        s = {x.id: x for x in router.pool.snapshots()}["a"]
        assert s.state == DEGRADED and s.retry_after_s == 7.0

    def test_pre_token_failover_sse(self, stub_router):
        """A replica that accepts the stream then dies before any token: the
        client transparently gets the full stream from the next replica, under
        one router id."""
        a, b = StubReplica(mode="engine_error_pre"), StubReplica(tokens=(7, 8, 9))
        router, port, reg = stub_router([("a", a), ("b", b)])
        status, body, _ = post_completion(
            port, {"prompt": [1, 2, 3], "max_tokens": 3, "stream": True})
        assert status == 200
        assert body["tokens"] == [7, 8, 9] and body["finish"] == "length"
        assert len(body["ids"]) == 1 and body["ids"].pop().startswith("rtr-")
        assert reg.get("paddlenlp_router_failovers_total").value() == 1
        assert reg.get("paddlenlp_router_requests_total").value(replica="b", outcome="ok") == 1
        # the failed replica is immediately demoted, not just excluded
        assert {x.id: x for x in router.pool.snapshots()}["a"].state != HEALTHY

    def test_pre_token_failover_batch(self, stub_router):
        a, b = StubReplica(mode="engine_error_mid"), StubReplica(tokens=(4, 5))
        router, port, reg = stub_router([("a", a), ("b", b)])
        status, body, _ = post_completion(port, {"prompt": [9], "max_tokens": 2})
        assert status == 200 and body["replica"] == "b"
        assert body["choices"][0]["token_ids"] == [4, 5]
        assert reg.get("paddlenlp_router_failovers_total").value() == 1

    def test_midstream_death_finishes_in_band(self, stub_router):
        """Tokens already relayed: no regeneration — the stream ends with
        finish_reason="replica_error" + usage, never a connection reset."""
        a = StubReplica(mode="die_midstream", tokens=(7, 8, 9, 10))
        b = StubReplica()
        router, port, reg = stub_router([("a", a), ("b", b)])
        status, body, _ = post_completion(
            port, {"prompt": [1, 2], "max_tokens": 4, "stream": True})
        assert status == 200
        assert body["tokens"] == [7, 8] and body["finish"] == "replica_error"
        assert body["final"]["usage"]["completion_tokens"] == 2
        assert body["final"]["usage"]["prompt_tokens"] == 2
        assert reg.get("paddlenlp_router_requests_total").value(
            replica="a", outcome="replica_error") == 1
        assert reg.get("paddlenlp_router_failovers_total").value() == 0
        assert len(b.requests) == 0  # never resubmitted

    def test_midstream_engine_error_becomes_replica_error(self, stub_router):
        a = StubReplica(mode="engine_error_mid", tokens=(7, 8, 9))
        router, port, reg = stub_router([("a", a)])
        status, body, _ = post_completion(
            port, {"prompt": [1], "max_tokens": 3, "stream": True})
        assert status == 200
        assert body["tokens"] == [7, 8] and body["finish"] == "replica_error"

    def test_router_forward_fault_point(self, stub_router):
        """router.forward armed: the first attempt fails like a socket error
        and the request lands on the next candidate."""
        a, b = StubReplica(), StubReplica(tokens=(1, 2))
        router, port, reg = stub_router([("a", a), ("b", b)])
        FAULTS.arm("router.forward", nth=1)
        status, body, _ = post_completion(port, {"prompt": [1], "max_tokens": 2})
        assert status == 200 and body["replica"] == "b"
        assert FAULTS.fired("router.forward") == 1
        assert reg.get("paddlenlp_router_rerouted_total").value() == 1
        assert len(a.requests) == 0  # fault fired before the connect

    def test_replica_500_fails_over_not_relayed(self, stub_router):
        """A replica-internal 500 (api.py's unexpected-exception mapping) is a
        replica failure, not a verdict on the request — the router must try
        the next candidate instead of relaying the 5xx."""
        a, b = StubReplica(mode="fail500"), StubReplica(tokens=(4, 5))
        router, port, reg = stub_router([("a", a), ("b", b)])
        status, body, _ = post_completion(port, {"prompt": [1], "max_tokens": 2})
        assert status == 200 and body["replica"] == "b"
        assert reg.get("paddlenlp_router_failovers_total").value() == 1
        # same on the SSE leg
        status, body, _ = post_completion(
            port, {"prompt": [2], "max_tokens": 2, "stream": True})
        assert status == 200 and body["tokens"] == [4, 5] and body["finish"] == "length"

    def test_forward_feedback_not_counted_as_probes(self, stub_router):
        """note_forward_failure must transition state without inventing
        health-poller bookkeeping (health_polls_total, last_poll_t)."""
        a = StubReplica()
        router, port, reg = stub_router([("a", a)])
        polls_before = reg.get("paddlenlp_router_health_polls_total").value(
            replica="a", outcome="error")
        last_poll_before = router.pool.get("a").last_poll_t
        router.pool.note_forward_failure("a")
        assert {x.id: x for x in router.pool.snapshots()}["a"].state == DEGRADED
        assert reg.get("paddlenlp_router_health_polls_total").value(
            replica="a", outcome="error") == polls_before
        assert router.pool.get("a").last_poll_t == last_poll_before

    def test_all_replicas_unavailable_clean_503(self, stub_router):
        a = StubReplica(mode="reject503")
        router, port, reg = stub_router([("a", a)])
        status, body, headers = post_completion(port, {"prompt": [1], "max_tokens": 2})
        assert status == 503
        assert body["error"]["type"] == "no_replica_available"
        assert int(headers.get("Retry-After", 0)) >= 1
        assert reg.get("paddlenlp_router_requests_total").value(
            replica="none", outcome="rejected") == 1

    def test_client_error_relayed_not_retried(self, stub_router):
        """A 400 from the replica is the request's fault: relay it verbatim,
        do not burn failover attempts on other replicas."""
        a, b = StubReplica(), StubReplica()
        router, port, reg = stub_router([("a", a), ("b", b)])
        status, body, _ = post_completion(port, {"max_tokens": 2})  # no prompt
        assert status == 400
        assert body["error"]["type"] == "invalid_request"
        assert len(a.requests) + len(b.requests) == 1

    def test_abort_routes_to_owning_replica(self, stub_router):
        # slow stream: the live-id window must stay open while the test finds it
        a = StubReplica(tokens=tuple(range(40)), token_delay_s=0.02)
        router, port, reg = stub_router([("a", a)])
        got = {}

        def worker():
            got["resp"] = post_completion(
                port, {"prompt": [1], "max_tokens": 40, "stream": True})

        t = threading.Thread(target=worker)
        t.start()
        # find the live router id, then abort through the router
        deadline = time.time() + 10
        rid = None
        while time.time() < deadline and rid is None:
            with router._live_lock:
                rid = next(iter(router._live), None)
            time.sleep(0.002)
        assert rid is not None
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/v1/abort", body=json.dumps({"id": rid}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        conn.close()
        assert out["cancelled"] is True
        assert len(a.aborts) == 1
        assert a.aborts[0]["id"].startswith("cmpl-")  # upstream id, not rtr-
        t.join(timeout=30)

    def test_negative_content_length_is_a_clean_400(self, stub_router):
        """Content-Length: -1 must not reach rfile.read(-1) (which would pin
        the handler thread until the client hangs up)."""
        a = StubReplica()
        router, port, reg = stub_router([("a", a)])
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.putrequest("POST", "/v1/completions")
        conn.putheader("Content-Type", "application/json")
        conn.putheader("Content-Length", "-1")
        conn.endheaders()
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        assert resp.status == 400
        assert "Content-Length" in body["error"]["message"]
        assert len(a.requests) == 0

    def test_down_replica_does_not_pin_retry_after_hint(self, stub_router):
        """A dead replica's stale Retry-After must not inflate the hint the
        router hands out after every candidate is exhausted."""
        a = StubReplica()
        router, port, reg = stub_router([("a", a)])
        router.pool.note_degraded("a", retry_after_s=120.0)
        assert router.pool.retry_after_hint() == 120.0
        for _ in range(router.pool.down_after):
            router.pool.note_forward_failure("a")
        assert {x.id: x for x in router.pool.snapshots()}["a"].state == DOWN
        assert router.pool.retry_after_hint() == 1.0  # floor, not the stale 120

    def test_router_span_names_do_not_collide_with_engine(self, stub_router):
        """The engine loop owns the span name "request" (with queue/prefill/
        decode phases under one trace); the router's terminal span must use a
        distinct name or /debug/trace consumers pick the wrong timeline."""
        from paddlenlp_tpu.observability.tracer import TRACER

        a = StubReplica()
        router, port, reg = stub_router([("a", a)])
        status, body, _ = post_completion(port, {"prompt": [1], "max_tokens": 2})
        assert status == 200
        rid = body["id"]
        names = {s.name for s in TRACER.snapshot(trace=rid)}
        assert "router_request" in names and "route" in names
        assert "request" not in names

    def test_drain_deadline_fails_over_token_less_stream(self, stub_router):
        """A drain that outlives its deadline must fail the still-token-less
        stream over to a survivor via the pre-token resubmit path: same SSE
        connection, full token stream, zero 5xx."""
        a = StubReplica(tokens=(1, 2, 3), token_delay_s=5.0)  # token-less for 5s
        b = StubReplica(tokens=(7, 8, 9))
        router, port, reg = stub_router([("a", a), ("b", b)])
        got = {}

        def worker():
            got["resp"] = post_completion(
                port, {"prompt": [1], "max_tokens": 3, "stream": True}, timeout=60)

        t = threading.Thread(target=worker)
        t.start()
        deadline = time.time() + 10
        while time.time() < deadline and router._open_forwards_on("a") == 0:
            time.sleep(0.005)
        assert router._open_forwards_on("a") == 1
        status, doc, _ = admin_post(port, "/replicas/drain",
                                    {"id": "a", "deadline_s": 0.0})
        assert status == 200 and doc["drain"]["state"] == "draining"
        time.sleep(0.02)
        router.pool.poll_once()  # sweep: deadline expired -> eviction hook
        t.join(timeout=30)
        assert not t.is_alive()
        status, body, _ = got["resp"]
        assert status == 200
        assert body["tokens"] == [7, 8, 9] and body["finish"] == "length"
        assert reg.get("paddlenlp_router_failovers_total").value() == 1
        # a draining replica's eviction is deliberate, not a health incident
        assert {s.id: s for s in router.pool.snapshots()}["a"].state == HEALTHY
        router.pool.poll_once()  # live forwards now 0 -> drained
        assert router.pool.drain_status("a")["drained"] is True
        status, doc, _ = admin_delete(port, "/replicas/a")
        assert status == 200 and doc["replica"]["state"] == "removed"
        assert router.pool.drain_status("a")["state"] == "removed"

    def test_health_and_metrics_planes(self, stub_router):
        a = StubReplica(kv=0.75)
        router, port, reg = stub_router([("a", a)])
        router.pool.poll_once()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/health")
        resp = conn.getresponse()
        health = json.loads(resp.read())
        conn.close()
        assert resp.status == 200 and health["status"] == "ok"
        assert health["replicas"][0]["state"] == HEALTHY
        assert health["replicas"][0]["kv_utilization"] == 0.75
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        text = resp.read().decode()
        conn.close()
        assert resp.status == 200
        assert 'paddlenlp_router_replica_healthy{replica="a"} 1' in text
        from paddlenlp_tpu.observability import lint_exposition

        assert lint_exposition(text) == []


# --------------------------------------------------------------------- admin plane
def admin_post(port, path, payload, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), dict(resp.getheaders())
    finally:
        conn.close()


def admin_delete(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("DELETE", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), dict(resp.getheaders())
    finally:
        conn.close()


def admin_get(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


class TestMembership:
    def test_add_replica_live(self, stub_router):
        """POST /replicas joins a replica at runtime; it is probed before the
        200 returns, so the very next request can route on real health."""
        a = StubReplica(mode="reject429")  # saturated: traffic must move on
        router, port, reg = stub_router([("a", a)])
        c = StubReplica(tokens=(5, 6))
        try:
            status, doc, _ = admin_post(port, "/replicas",
                                        {"host": "127.0.0.1", "port": c.port, "id": "c"})
            assert status == 200 and doc["replica"]["id"] == "c"
            assert doc["replica"]["state"] == HEALTHY
            status, body, _ = post_completion(port, {"prompt": [1], "max_tokens": 2})
            assert status == 200 and body["replica"] == "c"
            assert body["choices"][0]["token_ids"] == [5, 6]
            assert reg.get("paddlenlp_router_membership_changes_total").value(op="add") == 1
            # duplicate id: clean 409, pool unchanged
            status, doc, _ = admin_post(port, "/replicas",
                                        {"host": "127.0.0.1", "port": c.port, "id": "c"})
            assert status == 409 and doc["error"]["type"] == "already_registered"
            assert len(router.pool) == 2
        finally:
            c.stop()

    def test_add_replica_validates_body(self, stub_router):
        a = StubReplica()
        router, port, reg = stub_router([("a", a)])
        status, doc, _ = admin_post(port, "/replicas", {"host": "127.0.0.1"})
        assert status == 400 and doc["error"]["type"] == "invalid_request"
        assert len(router.pool) == 1

    def test_drain_excludes_new_traffic_and_delete_409_until_drained(self, stub_router):
        a, b = StubReplica(tokens=(1, 2)), StubReplica(tokens=(7, 8))
        router, port, reg = stub_router([("a", a), ("b", b)])
        status, doc, _ = admin_post(port, "/replicas/drain", {"id": "a"})
        assert status == 200 and doc["drain"]["state"] == "draining"
        # a draining replica receives NO new requests
        status, body, _ = post_completion(port, {"prompt": [1], "max_tokens": 2})
        assert status == 200 and body["replica"] == "b"
        assert len(a.requests) == 0
        # … and the drain PROPAGATED to the replica itself (best-effort
        # off-thread POST /admin/drain), so direct traffic 503s there too
        deadline = time.time() + 5
        while time.time() < deadline and not a.drains:
            time.sleep(0.01)
        assert a.drains and a.drains[0].get("retry_after_s") == 30.0
        # removal refused until the drain lands (no sweep has run yet)
        status, doc, _ = admin_delete(port, "/replicas/a")
        assert status == 409 and doc["error"]["type"] == "drain_pending"
        assert len(router.pool) == 2
        # one sweep with zero live forwards completes the drain
        router.pool.poll_once()
        status, doc, _ = admin_delete(port, "/replicas/a")
        assert status == 200 and doc["replica"]["state"] == "removed"
        assert len(router.pool) == 1
        status, listing = admin_get(port, "/replicas")
        assert status == 200
        assert [r["id"] for r in listing["replicas"]] == ["b"]
        assert [t["id"] for t in listing["removed"]] == ["a"]
        assert reg.get("paddlenlp_router_membership_changes_total").value(op="remove") == 1

    def test_drain_unknown_replica_404(self, stub_router):
        a = StubReplica()
        router, port, reg = stub_router([("a", a)])
        status, doc, _ = admin_post(port, "/replicas/drain", {"id": "nope"})
        assert status == 404 and doc["error"]["type"] == "unknown_replica"
        status, doc, _ = admin_delete(port, "/replicas/nope")
        assert status == 404

    def test_force_delete_skips_drain(self, stub_router):
        a, b = StubReplica(), StubReplica()
        router, port, reg = stub_router([("a", a), ("b", b)])
        status, doc, _ = admin_delete(port, "/replicas/a?force=1")
        assert status == 200 and doc["replica"]["forced"] is True
        assert len(router.pool) == 1

    def test_membership_fault_point_leaves_pool_unchanged(self, stub_router):
        """router.membership armed: the mutation fails BEFORE any state change
        — clean 500, nothing draining, and the retry (fault spent) succeeds."""
        a, b = StubReplica(), StubReplica()
        router, port, reg = stub_router([("a", a), ("b", b)])
        FAULTS.arm("router.membership", nth=1)
        status, doc, _ = admin_post(port, "/replicas/drain", {"id": "a"})
        assert status == 500
        assert FAULTS.fired("router.membership") == 1
        assert router.pool.is_draining("a") is False
        status, doc, _ = admin_post(port, "/replicas/drain", {"id": "a"})
        assert status == 200
        assert router.pool.is_draining("a") is True

    def test_ring_repins_bounded_on_live_add(self, stub_router):
        """Adding a replica to a prefix-affinity router moves only ~1/N of
        prefixes (consistent hashing over live membership churn)."""
        stubs = [(f"r{i}", StubReplica()) for i in range(3)]
        router, port, reg = stub_router(stubs, policy="prefix_affinity")
        snaps_before = router.pool.snapshots()
        pins_before = {k: router.policy.select(snaps_before, prompt=[k, 3, 9])[0].id
                       for k in range(200)}
        d = StubReplica()
        try:
            status, doc, _ = admin_post(port, "/replicas",
                                        {"host": "127.0.0.1", "port": d.port, "id": "r3"})
            assert status == 200
            snaps_after = router.pool.snapshots()
            moved = sum(
                1 for k in range(200)
                if router.policy.select(snaps_after, prompt=[k, 3, 9])[0].id
                != pins_before[k])
            assert 0 < moved / 200 < 0.5, f"{moved}/200 prefixes re-pinned"
        finally:
            d.stop()


# --------------------------------------------------------------------- hedging
class TestHedging:
    def test_hedge_fires_and_wins_race(self, stub_router):
        """Primary stalls past the budget; the shadow answers first and the
        client gets ITS stream under one router id — the both-respond race
        (the primary eventually produces tokens too, into a torn-down leg)."""
        a = StubReplica(tokens=(1, 2, 3), token_delay_s=0.6)
        b = StubReplica(tokens=(7, 8, 9))
        router, port, reg = stub_router([("a", a), ("b", b)], hedge_after_s=0.08)
        status, body, _ = post_completion(
            port, {"prompt": [1], "max_tokens": 3, "stream": True})
        assert status == 200
        assert body["tokens"] == [7, 8, 9] and body["finish"] == "length"
        assert len(body["ids"]) == 1 and body["ids"].pop().startswith("rtr-")
        assert reg.get("paddlenlp_router_hedges_total").value(outcome="hedge_won") == 1
        assert len(a.requests) == 1 and len(b.requests) == 1
        assert reg.get("paddlenlp_router_requests_total").value(
            replica="b", outcome="ok") == 1
        # losing is not a health incident: the slow replica stays offered
        assert {s.id: s for s in router.pool.snapshots()}["a"].state == HEALTHY

    def test_primary_wins_after_hedge_fired(self, stub_router):
        a = StubReplica(tokens=(1, 2), token_delay_s=0.25)
        b = StubReplica(tokens=(7, 8), token_delay_s=2.0)
        router, port, reg = stub_router([("a", a), ("b", b)], hedge_after_s=0.08)
        status, body, _ = post_completion(
            port, {"prompt": [1], "max_tokens": 2, "stream": True})
        assert status == 200
        assert body["tokens"] == [1, 2] and body["finish"] == "length"
        assert reg.get("paddlenlp_router_hedges_total").value(outcome="primary_won") == 1
        assert len(b.requests) == 1  # the shadow really fired ...
        assert reg.get("paddlenlp_router_requests_total").value(
            replica="a", outcome="ok") == 1  # ... but the primary served

    def test_no_hedge_inside_budget(self, stub_router):
        a, b = StubReplica(tokens=(1, 2)), StubReplica(tokens=(7, 8))
        router, port, reg = stub_router([("a", a), ("b", b)], hedge_after_s=5.0)
        status, body, _ = post_completion(
            port, {"prompt": [1], "max_tokens": 2, "stream": True})
        assert status == 200 and body["tokens"] == [1, 2]
        assert len(b.requests) == 0
        for outcome in ("fired", "primary_won", "hedge_won", "capped", "failed"):
            assert reg.get("paddlenlp_router_hedges_total").value(outcome=outcome) == 0

    def test_hedge_cap_suppresses_shadow(self, stub_router):
        a = StubReplica(tokens=(1, 2), token_delay_s=0.3)
        b = StubReplica(tokens=(7, 8))
        router, port, reg = stub_router([("a", a), ("b", b)],
                                        hedge_after_s=0.05, max_hedges_inflight=0)
        status, body, _ = post_completion(
            port, {"prompt": [1], "max_tokens": 2, "stream": True})
        assert status == 200
        assert body["tokens"] == [1, 2]  # primary still serves, just slowly
        assert len(b.requests) == 0
        assert reg.get("paddlenlp_router_hedges_total").value(outcome="capped") == 1

    def test_hedge_survives_primary_engine_error(self, stub_router):
        """Primary dies pre-token while the shadow is racing: the shadow's
        stream serves, the dead replica is excluded and demoted."""
        a = StubReplica(mode="engine_error_pre")
        b = StubReplica(tokens=(7, 8, 9), token_delay_s=0.2)
        router, port, reg = stub_router([("a", a), ("b", b)], hedge_after_s=0.05)
        status, body, _ = post_completion(
            port, {"prompt": [1], "max_tokens": 3, "stream": True})
        assert status == 200
        assert body["tokens"] == [7, 8, 9] and body["finish"] == "length"
        assert {s.id: s for s in router.pool.snapshots()}["a"].state != HEALTHY


class TestBatchHedging:
    """First-token hedging extended to non-stream /v1/completions: same
    loser-abort race and hedges_total accounting, over whole responses."""

    def test_batch_hedge_fires_and_wins(self, stub_router):
        a = StubReplica(tokens=(1, 2, 3), token_delay_s=0.3)  # ~0.9s to respond
        b = StubReplica(tokens=(7, 8, 9))
        router, port, reg = stub_router([("a", a), ("b", b)], hedge_after_s=0.08)
        status, doc, _ = post_completion(port, {"prompt": [1], "max_tokens": 3})
        assert status == 200
        assert doc["choices"][0]["token_ids"] == [7, 8, 9]
        assert doc["id"].startswith("rtr-") and doc["replica"] == "b"
        assert reg.get("paddlenlp_router_hedges_total").value(outcome="hedge_won") == 1
        assert len(a.requests) == 1 and len(b.requests) == 1
        assert reg.get("paddlenlp_router_requests_total").value(
            replica="b", outcome="ok") == 1
        # losing is not a health incident: the slow replica stays offered
        assert {s.id: s for s in router.pool.snapshots()}["a"].state == HEALTHY

    def test_batch_primary_wins_after_hedge_fired(self, stub_router):
        a = StubReplica(tokens=(1, 2), token_delay_s=0.15)
        b = StubReplica(tokens=(7, 8), token_delay_s=2.0)
        router, port, reg = stub_router([("a", a), ("b", b)], hedge_after_s=0.08)
        status, doc, _ = post_completion(port, {"prompt": [1], "max_tokens": 2})
        assert status == 200
        assert doc["choices"][0]["token_ids"] == [1, 2] and doc["replica"] == "a"
        assert reg.get("paddlenlp_router_hedges_total").value(outcome="primary_won") == 1
        assert len(b.requests) == 1  # the shadow really fired ...
        assert reg.get("paddlenlp_router_requests_total").value(
            replica="a", outcome="ok") == 1  # ... but the primary served

    def test_batch_no_hedge_inside_budget(self, stub_router):
        a, b = StubReplica(tokens=(1, 2)), StubReplica(tokens=(7, 8))
        router, port, reg = stub_router([("a", a), ("b", b)], hedge_after_s=5.0)
        status, doc, _ = post_completion(port, {"prompt": [1], "max_tokens": 2})
        assert status == 200 and doc["choices"][0]["token_ids"] == [1, 2]
        assert len(b.requests) == 0
        for outcome in ("fired", "primary_won", "hedge_won", "capped", "failed"):
            assert reg.get("paddlenlp_router_hedges_total").value(outcome=outcome) == 0

    def test_batch_hedge_cap_suppresses_shadow(self, stub_router):
        a = StubReplica(tokens=(1, 2), token_delay_s=0.15)
        b = StubReplica(tokens=(7, 8))
        router, port, reg = stub_router([("a", a), ("b", b)],
                                        hedge_after_s=0.05, max_hedges_inflight=0)
        status, doc, _ = post_completion(port, {"prompt": [1], "max_tokens": 2})
        assert status == 200
        assert doc["choices"][0]["token_ids"] == [1, 2]  # primary, just slowly
        assert len(b.requests) == 0
        assert reg.get("paddlenlp_router_hedges_total").value(outcome="capped") == 1

    def test_batch_hedge_survives_primary_engine_error(self, stub_router):
        """Primary answers an in-band engine_error while the shadow races: the
        shadow's response serves and the dead replica is excluded/demoted —
        classified by the same failure→disposition mapper as every leg."""
        a = StubReplica(mode="engine_error_pre")
        b = StubReplica(tokens=(7, 8, 9), token_delay_s=0.1)
        router, port, reg = stub_router([("a", a), ("b", b)], hedge_after_s=0.05)
        status, doc, _ = post_completion(port, {"prompt": [1], "max_tokens": 3})
        assert status == 200
        assert doc["choices"][0]["token_ids"] == [7, 8, 9] and doc["replica"] == "b"
        assert {s.id: s for s in router.pool.snapshots()}["a"].state != HEALTHY


class TestFailureClassification:
    """The single upstream-failure → disposition mapper (unit level)."""

    def test_http_date_retry_after_does_not_crash(self):
        from paddlenlp_tpu.serving.router.proxy import _classify_upstream_failure

        d = _classify_upstream_failure(
            "status", (503, b"", "Fri, 07 Aug 2026 07:28:00 GMT"))
        assert d.outcome == "reroute" and d.is_degraded
        assert d.retry_after_s() is None  # RFC 7231 date form: no hint, no crash
        assert _classify_upstream_failure(
            "status", (503, b"", "7")).retry_after_s() == 7.0

    def test_classification_table(self):
        from paddlenlp_tpu.serving.router.proxy import _classify_upstream_failure

        assert _classify_upstream_failure("connect_failed", OSError()).outcome == "reroute"
        assert _classify_upstream_failure("status", (429, b"", None)).outcome == "reroute"
        five = _classify_upstream_failure("status", (500, b"", None))
        assert five.outcome == "failover" and five.replica_fault
        relay = _classify_upstream_failure("status", (400, b"x", None))
        assert relay.outcome == "relay" and relay.raw == b"x" and not relay.replica_fault
        for kind in ("engine_error", "broke"):
            d = _classify_upstream_failure(kind, None)
            assert d.outcome == "failover" and d.replica_fault

    def test_request_level_503_does_not_degrade_replica(self):
        """A brownout shed / deadline reject is a healthy replica declining
        ONE request's class — re-route, but never mark it degraded (a
        fleet-wide brownout must not flap every replica to DEGRADED)."""
        import json as _json

        from paddlenlp_tpu.serving.router.proxy import _classify_upstream_failure

        for etype in ("overloaded_shed", "deadline_unmet"):
            body = _json.dumps({"error": {"type": etype, "message": "x"}}).encode()
            d = _classify_upstream_failure("status", (503, body, "2"))
            assert d.outcome == "reroute" and not d.is_degraded, etype
            assert d.retry_after_s() == 2.0
        # replica-level 503s (draining/degraded) still note degradation, and
        # an unparseable body reads as replica-level (conservative)
        drain = _json.dumps({"error": {"type": "shutting_down"}}).encode()
        assert _classify_upstream_failure("status", (503, drain, None)).is_degraded
        assert _classify_upstream_failure("status", (503, b"junk{", None)).is_degraded


class TestStageFold:
    """Fleet fold of disaggregated replicas' per-stage gauges into /fleet/slo."""

    def test_fold_stage_series(self):
        from paddlenlp_tpu.observability import parse_prometheus_text

        def expo(p_util, d_util, p_q):
            return (
                "# HELP paddlenlp_serving_stage_kv_utilization x\n"
                "# TYPE paddlenlp_serving_stage_kv_utilization gauge\n"
                f'paddlenlp_serving_stage_kv_utilization{{stage="prefill"}} {p_util}\n'
                f'paddlenlp_serving_stage_kv_utilization{{stage="decode"}} {d_util}\n'
                "# HELP paddlenlp_serving_stage_queue_depth x\n"
                "# TYPE paddlenlp_serving_stage_queue_depth gauge\n"
                f'paddlenlp_serving_stage_queue_depth{{stage="prefill"}} {p_q}\n')

        parsed = {"r0": parse_prometheus_text(expo(0.8, 0.2, 5)),
                  "r1": parse_prometheus_text(expo(0.4, 0.6, 1))}
        out = RouterServer._fold_stage_series(parsed)
        assert out["prefill"]["kv_utilization_max"] == 0.8
        assert out["prefill"]["kv_utilization_mean"] == pytest.approx(0.6)
        assert out["decode"]["kv_utilization_max"] == 0.6
        assert out["prefill"]["queue_depth_max"] == 5
        assert "queue_depth_max" not in out["decode"]  # series absent → no key

    def test_fold_empty_for_uniform_fleet(self):
        from paddlenlp_tpu.observability import parse_prometheus_text

        uniform = parse_prometheus_text(
            "# HELP paddlenlp_serving_kv_utilization x\n"
            "# TYPE paddlenlp_serving_kv_utilization gauge\n"
            "paddlenlp_serving_kv_utilization 0.5\n")
        assert RouterServer._fold_stage_series({"r0": uniform}) == {}
