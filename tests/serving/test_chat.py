"""Conversation-lifetime serving: /v1/chat/completions over the hierarchical
prefix cache.

- **prefix stability** (the invariant the whole feature rests on): turn N+1's
  rendered prompt begins with turn N's rendered prompt + its completion ids,
  by construction of :class:`ChatTemplate`;
- **multi-turn cache reuse over HTTP**: turn 2's ``usage.cached_tokens``
  covers turn 1's prompt AND completion (the engine registers generated
  blocks on finish), and the reply is token-exact against a fresh engine fed
  the same rendered ids;
- **SSE chat-chunk shapes**: role preamble first, per-token ``delta`` chunks,
  a final chunk carrying ``usage`` (with ``cached_tokens``), ``[DONE]``;
- **validation**: malformed conversations answer 400, never a 500 or a hang;
- **router conversation affinity**: a ``conversation`` key outranks adapter
  and prompt-prefix keys and pins every turn — whatever the prompt — to the
  same replica, deterministically.
"""

import http.client
import json

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import (
    ChatTemplate,
    MetricsRegistry,
    SchedulerConfig,
    ServingServer,
)
from paddlenlp_tpu.serving.router import HEALTHY, PrefixAffinityPolicy, ReplicaSnapshot
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

CFG = dict(vocab_size=96, hidden_size=64, intermediate_size=112,
           num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
           max_position_embeddings=256, eos_token_id=None, pad_token_id=0,
           use_scan_layers=True)
ENG_KW = dict(max_batch_size=4, block_size=4, num_blocks=64,
              max_blocks_per_seq=32, decode_steps=4,
              enable_prefix_cache=True, host_kv_blocks=64)
TPL = ChatTemplate()


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig(**CFG)


@pytest.fixture(scope="module")
def server(cfg):
    registry = MetricsRegistry()
    srv = ServingServer(
        InferenceEngine(LlamaForCausalLM.from_config(cfg, seed=0), **ENG_KW),
        registry=registry,
        scheduler_config=SchedulerConfig(max_inflight=8, default_timeout_s=600.0))
    port = srv.start_in_thread()
    yield srv, port
    srv.shutdown(drain_timeout_s=5)


@pytest.fixture(scope="module")
def solo(cfg):
    """Reference engine on the same weights: chat replies must be token-exact
    against generating from the rendered ids directly."""
    return InferenceEngine(LlamaForCausalLM.from_config(cfg, seed=0), **{
        **ENG_KW, "enable_prefix_cache": False, "host_kv_blocks": 0})


def post_json(port, path, payload, timeout=600):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def stream_chat(port, payload, timeout=600):
    """Returns (status, [raw chunk dicts], saw_done)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/chat/completions",
                     body=json.dumps({**payload, "stream": True}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            return resp.status, [json.loads(resp.read() or b"{}")], False
        events, done = [], False
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                done = True
                break
            events.append(json.loads(data))
        return resp.status, events, done
    finally:
        conn.close()


class TestChatTemplate:
    def test_render_shape_and_generation_marker(self):
        ids = TPL.render([{"role": "user", "content": [10, 11, 12]}],
                         encode=None)
        assert ids == [TPL.user_token_id, 10, 11, 12, TPL.sep_token_id,
                       TPL.assistant_token_id]

    def test_prefix_stability_across_turns(self):
        """The invariant: render(turn N+1) starts with render(turn N) +
        completion ids — checked over a 3-turn conversation with a system
        message."""
        msgs = [{"role": "system", "content": [30, 31]},
                {"role": "user", "content": [10, 11, 12]}]
        r1 = TPL.render(msgs, encode=None)
        completion1 = [40, 41, 42]
        msgs2 = msgs + [{"role": "assistant", "content": completion1},
                        {"role": "user", "content": [13, 14]}]
        r2 = TPL.render(msgs2, encode=None)
        assert r2[:len(r1) + len(completion1)] == r1 + completion1
        completion2 = [43, 44]
        msgs3 = msgs2 + [{"role": "assistant", "content": completion2},
                         {"role": "user", "content": [15]}]
        r3 = TPL.render(msgs3, encode=None)
        assert r3[:len(r2) + len(completion2)] == r2 + completion2

    def test_validation_errors(self):
        enc = None
        with pytest.raises(ValueError, match="non-empty"):
            TPL.render([], enc)
        with pytest.raises(ValueError, match="role"):
            TPL.render([{"role": "bot", "content": [5]}], enc)
        with pytest.raises(ValueError, match="system message"):
            TPL.render([{"role": "user", "content": [5]},
                        {"role": "system", "content": [6]}], enc)
        with pytest.raises(ValueError, match="empty"):
            TPL.render([{"role": "user", "content": []}], enc)
        with pytest.raises(ValueError, match="assistant"):
            TPL.render([{"role": "user", "content": [5]},
                        {"role": "assistant", "content": [6]}], enc)
        with pytest.raises(ValueError, match="tokenizer"):
            TPL.render([{"role": "user", "content": "hello"}],
                       lambda s: (_ for _ in ()).throw(
                           ValueError("string message content needs a tokenizer")))


class TestMultiTurnOverHttp:
    def test_turn2_cached_tokens_cover_prompt_and_completion(self, server, solo):
        _srv, port = server
        user1 = list(range(10, 26))  # 16 tokens
        msgs = [{"role": "user", "content": user1}]
        status, t1 = post_json(port, "/v1/chat/completions",
                               {"messages": msgs, "max_tokens": 8})
        assert status == 200, t1
        assert t1["object"] == "chat.completion"
        msg = t1["choices"][0]["message"]
        assert msg["role"] == "assistant" and len(msg["token_ids"]) == 8
        assert t1["id"].startswith("chatcmpl-")
        assert t1["usage"]["cached_tokens"] == 0

        # token identity turn 1: the server generated from render(msgs)
        r1 = TPL.render(msgs, encode=None)
        want1 = solo.generate([r1], SamplingParams(max_new_tokens=8))[0]
        np.testing.assert_array_equal(msg["token_ids"], want1)

        # turn 2 threads the completion back as assistant token ids
        msgs2 = msgs + [{"role": "assistant", "content": msg["token_ids"]},
                        {"role": "user", "content": [30, 31, 32]}]
        status, t2 = post_json(port, "/v1/chat/completions",
                               {"messages": msgs2, "max_tokens": 8})
        assert status == 200, t2
        # turn-1 render (19 ids) + completion (8) = 27 shared ids -> every
        # full block of BOTH is served from cache: strictly more than the
        # turn-1 prompt alone could explain
        shared = len(r1) + 8
        assert t2["usage"]["cached_tokens"] >= shared // 4 * 4 > len(r1), \
            t2["usage"]
        r2 = TPL.render(msgs2, encode=None)
        want2 = solo.generate([r2], SamplingParams(max_new_tokens=8))[0]
        np.testing.assert_array_equal(t2["choices"][0]["message"]["token_ids"],
                                      want2)

    def test_sse_chat_chunk_shapes(self, server, solo):
        _srv, port = server
        msgs = [{"role": "user", "content": [50, 51, 52, 53]}]
        status, events, done = stream_chat(
            port, {"messages": msgs, "max_tokens": 6,
                   "conversation": "sse-shape"})
        assert status == 200 and done
        assert all(ev["object"] == "chat.completion.chunk" for ev in events)
        # role preamble first, no token on it
        first = events[0]["choices"][0]
        assert first["delta"] == {"role": "assistant"}
        assert first["finish_reason"] is None
        toks = [ev["choices"][0]["delta"]["token"] for ev in events[1:-1]]
        assert len(toks) == 6
        final = events[-1]
        assert final["choices"][0]["finish_reason"] == "length"
        usage = final["usage"]
        assert set(usage) == {"prompt_tokens", "cached_tokens",
                              "completion_tokens", "total_tokens"}
        assert usage["completion_tokens"] == 6
        assert usage["prompt_tokens"] == len(TPL.render(msgs, encode=None))
        want = solo.generate([TPL.render(msgs, encode=None)],
                             SamplingParams(max_new_tokens=6))[0]
        np.testing.assert_array_equal(toks, want)

    def test_validation_is_400_over_http(self, server):
        _srv, port = server
        cases = [
            {"max_tokens": 4},  # no messages
            {"messages": [{"role": "user", "content": [5]}],
             "prompt": [5, 6]},  # both surfaces
            {"messages": []},
            {"messages": [{"role": "bot", "content": [5]}]},
            {"messages": [{"role": "user", "content": []}]},
            {"messages": [{"role": "user", "content": [5]}],
             "conversation": 7},  # non-string key
            {"messages": [{"role": "user", "content": "hi"}]},  # no tokenizer
        ]
        for payload in cases:
            status, body = post_json(port, "/v1/chat/completions",
                                     {**payload, "max_tokens": 4})
            assert status == 400, (payload, body)
            assert body["error"]["type"] == "invalid_request", body


def snap(rid, state=HEALTHY, inflight=0):
    return ReplicaSnapshot(id=rid, host="127.0.0.1", port=0, state=state,
                           inflight=inflight, queue_depth=0, kv_utilization=0.0,
                           retry_after_s=None, consecutive_failures=0,
                           last_poll_t=None)


class TestConversationAffinity:
    def test_key_precedence(self):
        pol = PrefixAffinityPolicy()
        assert pol.prefix_key([1, 2, 3]) == "t:1,2,3"
        assert pol.prefix_key([1, 2, 3], adapter_id="fr") == "a:fr"
        assert pol.prefix_key([1, 2, 3], adapter_id="fr",
                              conversation="conv-9") == "c:conv-9"
        assert pol.prefix_key(None, conversation="conv-9") == "c:conv-9"

    def test_conversation_sticks_across_changing_prompts(self):
        """Every turn of one conversation — the prompt GROWS each turn — pins
        to the same replica; distinct conversations spread over the ring."""
        pol = PrefixAffinityPolicy()
        replicas = [snap(f"r{i}") for i in range(4)]
        prompt = list(range(10, 30))
        picks = set()
        for turn in range(5):
            prompt = prompt + [40 + turn] * 8  # turn-over-turn growth
            order = pol.select(replicas, prompt=prompt, conversation="conv-a")
            picks.add(order[0].id)
        assert len(picks) == 1
        spread = {pol.select(replicas, prompt=prompt,
                             conversation=f"conv-{i}")[0].id
                  for i in range(16)}
        assert len(spread) > 1

    def test_no_conversation_falls_back_to_prefix(self):
        pol = PrefixAffinityPolicy()
        replicas = [snap(f"r{i}") for i in range(4)]
        a = pol.select(replicas, prompt=[1, 2, 3, 4])
        b = pol.select(replicas, prompt=[1, 2, 3, 4], conversation=None)
        assert a[0].id == b[0].id
