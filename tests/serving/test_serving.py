"""Serving runtime end-to-end on CPU: engine loop + scheduler + HTTP API.

Acceptance path (ISSUE 1): >=8 concurrent HTTP requests through the
continuous-batching engine loop with SSE streaming, one cancelled mid-stream,
one rejected 429 at saturation, /metrics exposing nonzero TTFT / queue-depth /
KV-utilization series."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import (
    EngineLoop,
    MetricsRegistry,
    Scheduler,
    SchedulerConfig,
    ServingServer,
    ShuttingDownError,
)
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine(model, **kw):
    defaults = dict(max_batch_size=4, block_size=4, num_blocks=128, max_blocks_per_seq=32,
                    decode_steps=4)
    defaults.update(kw)
    return InferenceEngine(model, **defaults)


# --------------------------------------------------------------------- engine hooks
class TestEngineHooks:
    def test_timing_fields_on_finished_request(self, model):
        eng = make_engine(model)
        eng.add_request([5, 6, 7], SamplingParams(max_new_tokens=6))
        done = []
        while eng.has_work():
            done += eng.step()
        (req,) = done
        assert req.finish_reason == "length"
        assert req.sched_t is not None and req.first_token_t is not None and req.finish_t is not None
        assert req.arrival_t <= req.sched_t <= req.first_token_t <= req.finish_t
        assert req.queue_wait >= 0 and req.ttft >= req.queue_wait and req.decode_time >= 0

    def test_abort_waiting_request(self, model):
        eng = make_engine(model)
        rid = eng.add_request([5, 6, 7], SamplingParams(max_new_tokens=6))
        req = eng.abort(rid)
        assert req is not None and req.aborted and req.finish_reason == "abort"
        assert not eng.has_work()
        assert eng.abort(rid) is None  # already gone

    def test_abort_running_request_frees_blocks(self, model):
        eng = make_engine(model)
        total = eng.mgr.num_free
        rid = eng.add_request([5, 6, 7, 8], SamplingParams(max_new_tokens=32))
        eng.step()  # prefill + some decode; request now holds blocks
        assert eng.mgr.num_free < total
        req = eng.abort(rid)
        assert req is not None and req.aborted
        assert eng.mgr.num_free == total  # KV fully reclaimed
        assert not eng.has_work()

    def test_step_cb_stats(self, model):
        eng = make_engine(model)
        seen = []
        eng.step_cb = seen.append
        eng.add_request([5, 6, 7], SamplingParams(max_new_tokens=4))
        while eng.has_work():
            eng.step()
        assert seen and {"queue_depth", "running", "free_blocks", "num_preemptions"} <= set(seen[0])


# --------------------------------------------------------------------- engine loop
class TestEngineLoop:
    def test_submit_matches_sync_generate(self, model):
        want = make_engine(model).generate([[5, 6, 7, 8, 9]], SamplingParams(max_new_tokens=8))[0]
        loop = EngineLoop(make_engine(model), registry=MetricsRegistry()).start()
        try:
            h = loop.submit([5, 6, 7, 8, 9], SamplingParams(max_new_tokens=8))
            streamed = list(h.tokens(timeout=120))
            req = h.result(timeout=5)
            np.testing.assert_array_equal(req.output_ids, want)
            np.testing.assert_array_equal(streamed, want)  # stream order == result order
        finally:
            loop.stop()

    def test_concurrent_submitters(self, model):
        loop = EngineLoop(make_engine(model), registry=MetricsRegistry()).start()
        prompts = [[5 + i, 6 + i, 7 + i] for i in range(6)]
        results = {}

        def worker(i):
            h = loop.submit(prompts[i], SamplingParams(max_new_tokens=6))
            results[i] = h.result(timeout=180).output_ids

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert len(results) == 6 and all(len(v) == 6 for v in results.values())
            # each prompt's tokens must match a solo run (batch isolation)
            solo = make_engine(model).generate([prompts[0]], SamplingParams(max_new_tokens=6))[0]
            np.testing.assert_array_equal(results[0], solo)
        finally:
            loop.stop()

    def test_cancel_midstream_frees_blocks(self, model):
        # max_new_tokens must FIT the per-seq KV cap (128 tokens here) or the
        # engine fail-fasts the request with finish_reason="capacity"
        eng = make_engine(model)
        total = eng.mgr.num_free
        loop = EngineLoop(eng, registry=MetricsRegistry()).start()
        try:
            h = loop.submit([5, 6, 7], SamplingParams(max_new_tokens=96))
            it = h.tokens(timeout=120)
            next(it)  # at least one token streamed
            loop.cancel(h)
            req = h.result(timeout=30)
            assert req.aborted and req.finish_reason == "abort"
            assert 0 < len(req.output_ids) < 96
            deadline = time.time() + 10
            while eng.mgr.num_free != total and time.time() < deadline:
                time.sleep(0.01)
            assert eng.mgr.num_free == total
        finally:
            loop.stop()

    def test_capacity_fail_fast(self, model):
        """A request that can never fit resolves immediately (no hang)."""
        loop = EngineLoop(make_engine(model), registry=MetricsRegistry()).start()
        try:
            h = loop.submit([5, 6, 7], SamplingParams(max_new_tokens=4096))
            req = h.result(timeout=60)
            assert req.finish_reason == "capacity" and req.output_ids == []
        finally:
            loop.stop()

    def test_deadline_timeout_aborts(self, model):
        loop = EngineLoop(make_engine(model), registry=MetricsRegistry()).start()
        try:
            h = loop.submit([5, 6, 7], SamplingParams(max_new_tokens=96), deadline_s=0.0)
            req = h.result(timeout=60)
            assert h.timed_out and req.aborted
        finally:
            loop.stop()

    def test_scheduler_drain_rejects(self, model):
        loop = EngineLoop(make_engine(model), registry=MetricsRegistry()).start()
        sched = Scheduler(loop, SchedulerConfig(max_inflight=4))
        try:
            h = sched.submit([5, 6, 7], SamplingParams(max_new_tokens=4))
            assert sched.drain(timeout_s=120)  # waits for the in-flight request
            assert h.done()
            with pytest.raises(ShuttingDownError):
                sched.submit([5, 6, 7], SamplingParams(max_new_tokens=4))
        finally:
            loop.stop()


# --------------------------------------------------------------------- http helpers
def post_json(port, path, payload, timeout=180):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


class SSEStream:
    """One streaming completion over a raw HTTP connection."""

    def __init__(self, port, payload, timeout=180):
        self.conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        self.conn.request("POST", "/v1/completions", body=json.dumps(payload),
                          headers={"Content-Type": "application/json"})
        self.resp = self.conn.getresponse()
        self.status = self.resp.status

    def events(self):
        """Yield parsed `data:` payloads until [DONE] or EOF."""
        while True:
            line = self.resp.readline()
            if not line:
                return
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                return
            yield json.loads(data)

    def close(self):
        self.conn.close()


@pytest.fixture(scope="module")
def server(model):
    registry = MetricsRegistry()
    srv = ServingServer(
        make_engine(model),
        scheduler_config=SchedulerConfig(max_inflight=9, default_timeout_s=300.0),
        registry=registry,
    )
    port = srv.start_in_thread()
    yield srv, port, registry
    srv.shutdown(drain_timeout_s=5)


# --------------------------------------------------------------------- http e2e
class TestServingHTTP:
    def test_e2e_concurrent_stream_cancel_saturate_metrics(self, server):
        srv, port, registry = server
        n_stream, gen_len = 8, 32
        # barrier releases once every stream's 200 response HEADERS arrived —
        # i.e. all 9 passed admission (window full) but none can have finished
        # yet (each needs >= gen_len tokens and the engine is still compiling)
        admitted = threading.Barrier(n_stream + 2, timeout=300)
        results = {}
        cancel_info = {"cid_ready": threading.Event()}

        def stream_worker(i):
            s = SSEStream(port, {"prompt": [5 + i, 6 + i, 7 + i],
                                 "max_tokens": gen_len, "stream": True})
            assert s.status == 200
            admitted.wait()
            toks, finish = [], None
            for ev in s.events():
                c = ev["choices"][0]
                if c.get("finish_reason"):
                    finish = c["finish_reason"]
                elif "token" in c:
                    toks.append(c["token"])
            results[i] = (toks, finish)
            s.close()

        def cancel_worker():
            s = SSEStream(port, {"prompt": [60, 61, 62], "max_tokens": 96, "stream": True})
            assert s.status == 200
            admitted.wait()
            n_toks = 0
            for ev in s.events():
                c = ev["choices"][0]
                if "token" in c:
                    n_toks += 1
                    if cancel_info.get("cid") is None:
                        cancel_info["cid"] = ev["id"]
                        cancel_info["cid_ready"].set()
                if c.get("finish_reason"):
                    cancel_info["finish"] = c["finish_reason"]
            cancel_info["n_toks"] = n_toks
            s.close()

        threads = [threading.Thread(target=stream_worker, args=(i,)) for i in range(n_stream)]
        ct = threading.Thread(target=cancel_worker)
        for t in threads + [ct]:
            t.start()

        admitted.wait()  # 9 in flight, window = 9: the next submit must shed
        status, body = post_json(port, "/v1/completions",
                                 {"prompt": [1, 2, 3], "max_tokens": 4})
        assert status == 429, body
        assert body["error"]["type"] == "rate_limit_exceeded"

        # cancel the long request once it is actually streaming
        assert cancel_info["cid_ready"].wait(timeout=300)
        status, body = post_json(port, "/v1/abort", {"id": cancel_info["cid"]})
        assert status == 200 and body["cancelled"] is True

        for t in threads + [ct]:
            t.join(timeout=600)

        # all 8 streams completed in order with the full token budget
        assert len(results) == n_stream
        for toks, finish in results.values():
            assert len(toks) == gen_len and finish == "length"
        # cancelled stream emitted some tokens then terminated with abort
        assert 0 < cancel_info["n_toks"] < 96
        assert cancel_info.get("finish") == "abort"

        # scrape the metrics plane
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        text = resp.read().decode()
        conn.close()

        def metric_value(name):
            for line in text.splitlines():
                if line.startswith(name + " ") or line.startswith(name + "{"):
                    return float(line.rsplit(" ", 1)[1])
            raise AssertionError(f"metric {name} missing from exposition:\n{text}")

        assert metric_value("paddlenlp_serving_ttft_seconds_count") >= 9
        assert metric_value("paddlenlp_serving_ttft_seconds_sum") > 0
        assert 'paddlenlp_serving_requests_total{status="length",priority="interactive",tenant="default"}' in text
        assert 'paddlenlp_serving_requests_total{status="abort",priority="interactive",tenant="default"}' in text
        assert metric_value("paddlenlp_serving_queue_depth") >= 0  # series present
        assert metric_value("paddlenlp_serving_kv_utilization") >= 0
        assert metric_value("paddlenlp_serving_tokens_generated_total") >= n_stream * gen_len
        # saturation rejection is visible via /health scheduler stats
        status, health = post_json_get(port, "/health")
        assert health["scheduler"]["rejected_saturated"] >= 1

    def test_batch_mode_with_timing(self, server):
        srv, port, _ = server
        status, body = post_json(port, "/v1/completions", {"prompt": [9, 10, 11], "max_tokens": 5})
        assert status == 200
        choice = body["choices"][0]
        assert len(choice["token_ids"]) == 5 and choice["finish_reason"] == "length"
        assert body["usage"] == {"prompt_tokens": 3, "cached_tokens": 0,
                                 "completion_tokens": 5, "total_tokens": 8}
        assert body["timing"]["ttft_s"] > 0

    def test_http_errors(self, server):
        srv, port, _ = server
        status, body = post_json(port, "/v1/completions", {"max_tokens": 4})
        assert status == 400  # missing prompt
        status, body = post_json(port, "/v1/completions", {"prompt": "hi"})
        assert status == 400  # string prompt without tokenizer
        status, body = post_json(port, "/nope", {})
        assert status == 404
        status, body = post_json(port, "/v1/abort", {"id": "cmpl-unknown"})
        assert status == 200 and body["cancelled"] is False

    def test_oversized_body_413(self, server):
        srv, port, _ = server
        old = srv.max_body_bytes
        srv.max_body_bytes = 64
        try:
            status, body = post_json(port, "/v1/completions",
                                     {"prompt": list(range(64)), "max_tokens": 1})
            assert status == 413
        finally:
            srv.max_body_bytes = old

    def test_health(self, server):
        srv, port, _ = server
        status, body = post_json_get(port, "/health")
        assert status == 200 and body["status"] == "ok"
        assert "free_blocks" in body["engine"] and "inflight" in body["scheduler"]


def post_json_get(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


# --------------------------------------------------------------------- SimpleServer
class TestSimpleServerHardening:
    def test_oversized_body_413(self):
        from paddlenlp_tpu.server import SimpleServer

        srv = SimpleServer(max_body_bytes=32)
        srv._routes["/models/echo"] = lambda data, params: data
        port = srv.start_in_thread()
        try:
            status, body = post_json(port, "/models/echo", {"data": "x" * 128})
            assert status == 413
            status, body = post_json(port, "/models/echo", {"data": "hi"})
            assert status == 200 and body["result"] == "hi"
        finally:
            srv.shutdown()


# --------------------------------------------------------------------- drain propagation
class TestReplicaSideDrain:
    """POST /admin/drain: a drained ServingServer 503s new DIRECT traffic
    (with Retry-After) while accepted streams finish — the replica-side half
    of the router's admin-plane drain."""

    def test_direct_traffic_503_while_inflight_finishes(self, model):
        import http.client as hc

        srv = ServingServer(
            make_engine(model),
            scheduler_config=SchedulerConfig(max_inflight=8, default_timeout_s=300.0),
            registry=MetricsRegistry(),
        )
        port = srv.start_in_thread()
        try:
            # open a stream BEFORE the drain: it must finish normally
            s = SSEStream(port, {"prompt": [5, 6, 7], "max_tokens": 6, "stream": True})
            assert s.status == 200
            status, doc = post_json(port, "/admin/drain", {"retry_after_s": 12})
            assert status == 200 and doc["draining"] is True
            assert doc["retry_after_s"] == 12.0
            # new direct traffic: clean 503 + Retry-After, no connection reset
            conn = hc.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request("POST", "/v1/completions",
                         body=json.dumps({"prompt": [1, 2], "max_tokens": 2}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 503
            assert body["error"]["type"] == "shutting_down"
            assert int(resp.getheader("Retry-After")) == 12
            conn.close()
            # /health reports draining with the same hint
            conn = hc.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request("GET", "/health")
            resp = conn.getresponse()
            health = json.loads(resp.read())
            assert resp.status == 503 and health["status"] == "draining"
            assert int(resp.getheader("Retry-After")) == 12
            conn.close()
            # the pre-drain stream still completes token-for-token
            toks = [ev["choices"][0]["token"] for ev in s.events()
                    if "token" in ev["choices"][0]]
            s.close()
            assert len(toks) == 6
        finally:
            srv.shutdown(drain_timeout_s=5)

    def test_admin_drain_validates_body(self, model):
        srv = ServingServer(make_engine(model), registry=MetricsRegistry())
        port = srv.start_in_thread()
        try:
            status, doc = post_json(port, "/admin/drain", {"retry_after_s": "soon"})
            assert status == 400 and doc["error"]["type"] == "invalid_request"
            # the malformed request must NOT have drained the server
            status, doc = post_json(port, "/v1/completions",
                                    {"prompt": [1, 2], "max_tokens": 2})
            assert status == 200
        finally:
            srv.shutdown(drain_timeout_s=5)
