"""Live base-checkpoint hot-swap, replica side (POST /admin/weights).

The swap contract under test:

- **door checks are 409s, not mutations**: a missing / torn / uncommitted
  checkpoint and a dimension conflict against the live model config all
  answer ``weights_conflict`` with the engine untouched;
- **all-or-nothing install**: a canary-digest mismatch rolls back to the
  retained old params — the replica keeps serving (and reporting) the
  version it served before;
- **finish_old quiesce**: streams in flight when the swap lands finish
  token-exact under the OLD weights, and the first post-swap request is
  token-exact against a fresh engine built on the NEW weights;
- **cache-epoch regression (HTTP path)**: prefix blocks registered before
  the swap never serve a post-swap request, and a stream that finishes
  after the epoch bump (pause_resume) must not re-register its pre-swap KV
  into the new epoch.
"""

import http.client
import json
import shutil
import threading
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import MetricsRegistry, SchedulerConfig, ServingServer
from paddlenlp_tpu.serving.engine_loop import CANARY_PROMPT_IDS, canary_digest
from paddlenlp_tpu.trainer.unified_checkpoint import save_unified_checkpoint
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS

CFG = dict(vocab_size=96, hidden_size=64, intermediate_size=112,
           num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
           max_position_embeddings=256, eos_token_id=None, pad_token_id=0,
           use_scan_layers=True)
ENG_KW = dict(max_batch_size=4, block_size=4, num_blocks=256,
              max_blocks_per_seq=32, decode_steps=4)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig(**CFG)


@pytest.fixture(scope="module")
def ckpts(cfg, tmp_path_factory):
    """On-disk checkpoint fixtures: v0/v1 committed (seed 0/1 weights), a
    torn copy of v1 (commit manifest deleted), and a dimension-conflicting
    committed checkpoint (half-width model)."""
    root = tmp_path_factory.mktemp("weights")
    save_unified_checkpoint(str(root / "v0"),
                            LlamaForCausalLM.from_config(cfg, seed=0), None)
    save_unified_checkpoint(str(root / "v1"),
                            LlamaForCausalLM.from_config(cfg, seed=1), None)
    shutil.copytree(root / "v1", root / "torn")
    (root / "torn" / "commit.json").unlink()
    narrow = LlamaConfig(**{**CFG, "hidden_size": 32, "intermediate_size": 64})
    save_unified_checkpoint(str(root / "narrow"),
                            LlamaForCausalLM.from_config(narrow, seed=0), None)
    return root


@pytest.fixture(scope="module")
def solo_old(cfg):
    return InferenceEngine(LlamaForCausalLM.from_config(cfg, seed=0), **ENG_KW)


@pytest.fixture(scope="module")
def solo_new(cfg):
    return InferenceEngine(LlamaForCausalLM.from_config(cfg, seed=1), **ENG_KW)


@pytest.fixture
def server(cfg):
    """A fresh replica per test — swap tests mutate the served weights, so
    nothing may be shared. Each replica gets its OWN model instance: the
    single-device backend installs params by rebinding ``model.params``."""
    registry = MetricsRegistry()
    srv = ServingServer(
        InferenceEngine(LlamaForCausalLM.from_config(cfg, seed=0), **ENG_KW),
        registry=registry,
        scheduler_config=SchedulerConfig(max_inflight=8, default_timeout_s=600.0))
    port = srv.start_in_thread()
    yield srv, port, registry
    srv.shutdown(drain_timeout_s=5)


def post_json(port, path, payload, timeout=300):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def get_json(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def stream_request(port, prompt, max_tokens, out, key, timeout=600, **extra):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions",
                     body=json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                                      "stream": True, **extra}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        toks, finish = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                break
            ev = json.loads(data)
            c = ev["choices"][0]
            if c.get("finish_reason"):
                finish = c["finish_reason"]
            elif "token" in c:
                toks.append(c["token"])
        out[key] = (resp.status, toks, finish)
    finally:
        conn.close()


def wait_decoding(srv, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if any(r.get("output_tokens", 0) > 0 for r in srv.loop.inflight_info()):
            return
        time.sleep(0.005)
    raise AssertionError("stream never started decoding")


def assert_no_kv_leak(srv):
    mgr = srv.loop.engine.mgr
    assert mgr.num_free == mgr.total_usable_blocks, \
        f"KV leak: {mgr.total_usable_blocks - mgr.num_free} blocks still held"


def new_canary_digest(solo_new):
    return canary_digest(solo_new.generate([list(CANARY_PROMPT_IDS)], None)[0])


PROMPT = [11, 12, 13, 14, 15, 16]


class TestSwapDoorChecks:
    """Every rejection answers 409 with the engine untouched — the same
    replica keeps serving v0, token-exact, after the whole gauntlet."""

    def test_conflicts_are_409_and_engine_untouched(self, server, ckpts, solo_old):
        srv, port, _registry = server
        status, body = post_json(port, "/admin/weights", {})
        assert status == 400, body

        for bad, needle in [
            (str(ckpts / "missing"), "not swappable"),
            (str(ckpts / "torn"), "not swappable"),
            (str(ckpts / "narrow"), "hidden_size"),
        ]:
            status, body = post_json(port, "/admin/weights", {"ckpt_dir": bad})
            assert status == 409, (bad, body)
            assert body["error"]["type"] == "weights_conflict", body
            assert needle in body["error"]["message"], body

        status, health = get_json(port, "/health")
        assert status == 200 and health["weights_version"] == "v0"
        status, body = post_json(port, "/v1/completions",
                                 {"prompt": PROMPT, "max_tokens": 8})
        assert status == 200
        want = solo_old.generate([PROMPT], SamplingParams(max_new_tokens=8))[0]
        np.testing.assert_array_equal(body["choices"][0]["token_ids"], want)

    def test_canary_mismatch_rolls_back(self, server, ckpts, solo_old):
        srv, port, _registry = server
        status, body = post_json(port, "/admin/weights",
                                 {"ckpt_dir": str(ckpts / "v1"),
                                  "canary_digest": "0" * 64})
        assert status == 409, body
        assert body["ok"] is False and body["rolled_back"] is True
        assert body["reason"] == "canary_mismatch"
        # the replica still serves v0 — version AND tokens
        _, health = get_json(port, "/health")
        assert health["weights_version"] == "v0"
        status, body = post_json(port, "/v1/completions",
                                 {"prompt": PROMPT, "max_tokens": 8})
        assert status == 200
        want = solo_old.generate([PROMPT], SamplingParams(max_new_tokens=8))[0]
        np.testing.assert_array_equal(body["choices"][0]["token_ids"], want)
        assert_no_kv_leak(srv)


class TestSwapFinishOld:
    def test_inflight_finish_old_then_new_weights_serve(
            self, server, ckpts, solo_old, solo_new):
        srv, port, registry = server
        results = {}
        threads = [threading.Thread(
            target=stream_request,
            args=(port, PROMPT + [30 + i], 24, results, i)) for i in range(2)]
        for t in threads:
            t.start()
        wait_decoding(srv)

        expected = new_canary_digest(solo_new)
        status, doc = post_json(port, "/admin/weights",
                                {"ckpt_dir": str(ckpts / "v1"),
                                 "canary_digest": expected})
        assert status == 200, doc
        assert doc["ok"] is True and doc["weights_version"] == "v1"
        assert doc["canary_digest"] == expected
        # finish_old: nothing was paused/resumed — token identity holds
        assert doc["resumed"] == 0 and doc["token_identity"] is True

        for t in threads:
            t.join(timeout=600)
        for i in range(2):
            status, toks, finish = results[i]
            assert status == 200 and finish == "length", (i, results[i])
            want = solo_old.generate(
                [PROMPT + [30 + i]], SamplingParams(max_new_tokens=24))[0]
            np.testing.assert_array_equal(toks, want)

        # the replica now serves the NEW weights, token-exact vs fresh-start
        status, body = post_json(port, "/v1/completions",
                                 {"prompt": PROMPT, "max_tokens": 8})
        assert status == 200
        want = solo_new.generate([PROMPT], SamplingParams(max_new_tokens=8))[0]
        np.testing.assert_array_equal(body["choices"][0]["token_ids"], want)

        _, health = get_json(port, "/health")
        assert health["weights_version"] == "v1"
        expo = registry.expose()
        assert 'paddlenlp_serving_weights_info{version="v1"} 1' in expo
        assert 'version="v0"' not in expo
        assert_no_kv_leak(srv)


class TestHostTierAcrossSwap:
    def test_swap_invalidates_host_tier(self, cfg, ckpts, solo_new):
        """The hierarchical cache's second level obeys the same epoch: KV
        spilled to host RAM before a weight swap must never promote into
        post-swap traffic (it holds OLD-weights activations)."""
        # a replica with a SMALL device pool over a host tier, so churn
        # demotes the prompt's blocks to host RAM instead of destroying them
        srv = ServingServer(
            InferenceEngine(LlamaForCausalLM.from_config(cfg, seed=0),
                            max_batch_size=4, block_size=4, num_blocks=15,
                            max_blocks_per_seq=16, decode_steps=4,
                            enable_prefix_cache=True, host_kv_blocks=64),
            registry=MetricsRegistry(),
            scheduler_config=SchedulerConfig(max_inflight=8,
                                             default_timeout_s=600.0))
        port = srv.start_in_thread()
        try:
            prompt = list(range(30, 46))  # 4 full blocks
            status, _ = post_json(port, "/v1/completions",
                                  {"prompt": prompt, "max_tokens": 8})
            assert status == 200
            status, _ = post_json(port, "/v1/completions",
                                  {"prompt": [40 + i % 50 for i in range(52)],
                                   "max_tokens": 4})
            assert status == 200
            eng = srv.loop.engine
            assert eng._host_tier.num_blocks > 0, "churn never spilled"
            # the tier is LIVE pre-swap: the repeat promotes from host RAM
            status, b = post_json(port, "/v1/completions",
                                  {"prompt": prompt, "max_tokens": 8})
            assert status == 200 and b["usage"]["cached_tokens"] > 0
            assert eng._host_tier.stats["promoted_blocks"] > 0
            assert eng._host_tier.num_blocks > 0  # churn's own spilled blocks

            status, doc = post_json(port, "/admin/weights",
                                    {"ckpt_dir": str(ckpts / "v1")})
            assert status == 200 and doc["ok"] is True, doc
            # the swap emptied BOTH cache levels...
            assert eng._host_tier.num_blocks == 0
            promotes0 = eng._host_tier.stats["promotes"]
            status, c = post_json(port, "/v1/completions",
                                  {"prompt": prompt, "max_tokens": 8})
            assert status == 200
            # ...so the post-swap repeat prefills cold (no device hit, no
            # host promote) and is token-exact against fresh new weights
            assert c["usage"]["cached_tokens"] == 0, \
                "stale pre-swap KV served a post-swap request"
            assert eng._host_tier.stats["promotes"] == promotes0
            want = solo_new.generate([prompt],
                                     SamplingParams(max_new_tokens=8))[0]
            np.testing.assert_array_equal(c["choices"][0]["token_ids"], want)
            assert_no_kv_leak(srv)
        finally:
            srv.shutdown(drain_timeout_s=5)


class TestCacheEpochAcrossSwap:
    def test_pre_swap_prefix_blocks_never_serve_post_swap(
            self, server, ckpts, solo_new):
        srv, port, _registry = server
        # register PROMPT's blocks in the (old-weights) prefix index and
        # prove the index is live: the second identical request hits it
        status, a = post_json(port, "/v1/completions",
                              {"prompt": PROMPT, "max_tokens": 8})
        assert status == 200 and a["usage"]["cached_tokens"] == 0
        status, a2 = post_json(port, "/v1/completions",
                               {"prompt": PROMPT, "max_tokens": 8})
        assert status == 200 and a2["usage"]["cached_tokens"] > 0

        # a stream in flight ACROSS the epoch bump: pause_resume aborts it
        # engine-side and resumes it after the install, so it FINISHES after
        # clear_prefix_cache — its pre-swap KV must not re-register. Steps
        # are delay-faulted so the stream is still decoding when the swap
        # (door checks + checkpoint load take ~1s) reaches the loop.
        FAULTS.arm("engine.step", action="delay", delay_s=0.2, times=50)
        results = {}
        # a prompt disjoint from PROMPT: when the resumed stream finishes and
        # (validly) registers its re-prefilled new-weights KV, none of its
        # blocks can satisfy a PROMPT-prefix lookup
        c_prompt = [70, 71, 72, 73, 74, 75]
        t = threading.Thread(target=stream_request,
                             args=(port, c_prompt, 24, results, "c"))
        t.start()
        wait_decoding(srv)
        status, doc = post_json(port, "/admin/weights",
                                {"ckpt_dir": str(ckpts / "v1"),
                                 "mode": "pause_resume"})
        assert status == 200, doc
        assert doc["ok"] is True
        # the paused stream resumed under the new weights: explicitly NOT
        # token-identical, and the result doc says so
        assert doc["resumed"] == 1 and doc["token_identity"] is False
        t.join(timeout=600)
        status, toks, finish = results["c"]
        assert status == 200 and finish == "length" and len(toks) == 24

        # post-swap, the same prompt must prefill from scratch (zero cached
        # tokens — the old epoch is unreachable) and be token-exact against
        # a fresh engine on the new weights
        status, b = post_json(port, "/v1/completions",
                              {"prompt": PROMPT, "max_tokens": 8})
        assert status == 200, b
        assert b["usage"]["cached_tokens"] == 0, \
            "stale pre-swap KV served a post-swap request"
        want = solo_new.generate([PROMPT], SamplingParams(max_new_tokens=8))[0]
        np.testing.assert_array_equal(b["choices"][0]["token_ids"], want)

        # positive control: the resumed stream's re-prefill happened under
        # the NEW weights, so reusing ITS registered blocks is valid — a
        # c-prefixed request may hit the cache and must stay token-exact
        status, d = post_json(port, "/v1/completions",
                              {"prompt": c_prompt, "max_tokens": 8})
        assert status == 200
        want = solo_new.generate([c_prompt], SamplingParams(max_new_tokens=8))[0]
        np.testing.assert_array_equal(d["choices"][0]["token_ids"], want)
        assert_no_kv_leak(srv)
