"""Billing-grade usage metering integration tests.

The load-bearing invariant: over a mixed multi-tenant workload, the sum over
usage records of the engine-attributed ``useful_tokens`` equals the goodput
ledger's ``useful`` total **exactly** (zero slack — no preemption or rebuild
here), and per completed request ``prompt − cached + completion − 1 ==
useful`` (the −1 is the final sampled token: emitted but never fed). Checked
across the chunked-prefill × prefix-cache × tensor-parallel × disaggregated
matrix, because attribution rides every step path.

HTTP side: ``GET /debug/usage`` on the replica, ``GET /fleet/usage`` on the
router, ``usage_so_far`` on in-flight ``/debug/requests`` rows, the
``POST /admin/adapters`` fleet fan-out, and ``tools/usage_report.py``
agreeing with the router fold per tenant AND per adapter (plus rc 1 on a
hand-corrupted double bill).

CPU-only, tiny model — tier-1 speed."""

import http.client
import json
import os
import sys
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.observability.usage import UsageLedger, load_ledger_dir
from paddlenlp_tpu.serving import MetricsRegistry, SchedulerConfig, ServingServer
from paddlenlp_tpu.serving.engine_loop import EngineLoop
from paddlenlp_tpu.serving.tenancy import AdapterRegistry, UsageMeter
from paddlenlp_tpu.serving.tenancy.adapters import adapter_dims_from_config
from paddlenlp_tpu.serving.tenancy.metering import ENV_DIR
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import usage_report  # noqa: E402

ENG_KW = dict(max_batch_size=4, block_size=4, num_blocks=128,
              max_blocks_per_seq=32, decode_steps=4)
GEN = 8
TENANTS = ("acme", "globex", "initech")


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def get_json(port, path, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def post_json(port, path, payload, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


# ------------------------------------------------------- engine-level matrix
MATRIX = [
    pytest.param(dict(), id="mono"),
    pytest.param(dict(prefill_chunk_tokens=8), id="chunked"),
    pytest.param(dict(mesh_shape=(1, 2)), id="tp2"),
    pytest.param(dict(disagg_stages=(1, 1)), id="disagg"),
]

#: shared 8-token prefix (two full blocks at block_size=4) so the second
#: wave's admissions take a prefix-cache credit
PREFIX = [5, 6, 7, 8, 9, 10, 11, 12]


class TestReconciliationMatrix:
    @pytest.mark.parametrize("eng_kw", MATRIX)
    def test_metered_useful_equals_ledger_exactly(self, model, eng_kw):
        eng = InferenceEngine(model, enable_prefix_cache=True,
                              **dict(ENG_KW, **eng_kw))
        meter = UsageMeter()
        sampling = SamplingParams(max_new_tokens=GEN)
        records = []

        def run_wave(wave, n):
            ids = {}
            for i in range(n):
                tenant = TENANTS[i % len(TENANTS)]
                rid = eng.add_request(PREFIX + [20 + wave, 30 + i], sampling,
                                      tenant=tenant,
                                      trace=f"w{wave}-{i}")
                ids[rid] = tenant
            done = {}
            while eng.has_work():
                for req in eng.step():
                    done[req.req_id] = req
            assert set(done) == set(ids)
            for rid, req in done.items():
                rec = meter.record_finished(req)
                assert rec is not None
                assert rec["tenant"] == ids[rid]
                records.append(rec)
                # idempotency: re-resolving the same request books nothing
                assert meter.record_finished(req) is None

        run_wave(0, 4)
        run_wave(1, 4)  # same prefix: these admissions hit the prefix cache

        assert len(records) == 8
        assert len({r["record_id"] for r in records}) == 8

        # EXACT reconciliation: metered useful vs the goodput ledger's truth
        ledger_totals = eng.efficiency()["ledger"]["totals"]
        assert sum(r["useful_tokens"] for r in records) == ledger_totals["useful"]

        # per-request identity (no preemption here): everything the client
        # was billed for, minus the cache credit, minus the final sampled
        # token, was a useful fed position
        for r in records:
            assert (r["prompt_tokens"] - r["cached_tokens"]
                    + r["completion_tokens"] - 1) == r["useful_tokens"], r
            assert r["completion_tokens"] == GEN
            assert r["kv_block_seconds"] > 0.0
            assert r["finish_reason"] == "length"

        # wave 1 re-used wave 0's prefix KV: the credit is real and booked
        wave1 = [r for r in records if r["record_id"].startswith("w1-")]
        assert sum(r["cached_tokens"] for r in wave1) > 0
        # ... and only booked at FIRST admission, never exceeding the prompt
        for r in records:
            assert 0 <= r["cached_tokens"] <= r["prompt_tokens"]

        # the rolling aggregate folds the same records
        snap = meter.snapshot()
        assert snap["records"] == 8
        assert set(snap["tenants"]) == set(TENANTS)
        assert snap["totals"]["useful_tokens"] == ledger_totals["useful"]


# ------------------------------------------------------------ serving plane
class TestServingUsagePlane:
    def test_debug_usage_endpoint_and_counters(self, model, tmp_path, monkeypatch):
        monkeypatch.setenv(ENV_DIR, str(tmp_path / "ledger"))
        registry = MetricsRegistry()
        srv = ServingServer(InferenceEngine(model, **ENG_KW), registry=registry,
                            scheduler_config=SchedulerConfig(max_inflight=8))
        port = srv.start_in_thread()
        try:
            for i, tenant in enumerate(TENANTS):
                status, doc = post_json(port, "/v1/completions",
                                        {"prompt": [3 + i, 4, 5, 6],
                                         "max_tokens": 4, "tenant": tenant})
                assert status == 200, doc
            status, doc = get_json(port, "/debug/usage")
            assert status == 200
            assert doc["tier"] == "serving"
            assert doc["records"] == 3
            assert set(doc["tenants"]) == set(TENANTS)
            assert doc["adapters"]["base"]["records"] == 3
            assert doc["ledger"]["records_total"] == 3
            assert doc["engine_state"] == "running"

            # finished rows on /debug/requests carry the billed usage
            status, dbg = get_json(port, "/debug/requests")
            assert status == 200
            assert len(dbg["recent"]) == 3
            for row in dbg["recent"]:
                assert row["usage"]["completion_tokens"] == 4
                assert row["tenant"] in TENANTS

            # Prometheus counters labeled by payer
            exposition = registry.expose()
            assert ('paddlenlp_serving_usage_records_total{tenant="acme"} 1'
                    in exposition)
            assert 'kind="completion"' in exposition

            # postmortem bundles embed the aggregate
            health = srv.loop._postmortem_health()
            assert health["usage"]["records"] == 3
        finally:
            srv.shutdown(drain_timeout_s=5)
        # shutdown sealed the ledger: the durable view matches the rolling one
        records, report = load_ledger_dir(str(tmp_path / "ledger"))
        assert report["open_segments"] == 0
        assert len(records) == 3

    def test_inflight_rows_carry_usage_so_far(self, model):
        loop = EngineLoop(InferenceEngine(model, **ENG_KW),
                          registry=MetricsRegistry(), usage=UsageMeter())
        loop.start()
        try:
            handle = loop.submit([3, 4, 5, 6],
                                 SamplingParams(max_new_tokens=64))
            seen = None
            deadline = time.time() + 60
            while time.time() < deadline and not handle.done():
                rows = [r for r in loop.inflight_info()
                        if r.get("usage_so_far") is not None]
                if rows and rows[0]["usage_so_far"]["completion_tokens"] > 0:
                    seen = rows[0]["usage_so_far"]
                    break
                time.sleep(0.002)
            assert seen is not None, "never caught an in-flight usage row"
            assert seen["prompt_tokens"] == 4
            assert seen["kv_block_seconds"] > 0.0
            assert 0 < seen["completion_tokens"] <= 64
            req = handle.result(timeout=120)
            assert len(req.output_ids) == 64
        finally:
            loop.stop(drain=False)


# ------------------------------------------------------------- fleet + report
ADAPTER_IDS = ("ad-a", "ad-b")


def adapter_source(cfg, idx, rank=4):
    rng = np.random.default_rng(1000 + idx)
    return {proj: {"A": rng.standard_normal(
        (cfg.num_hidden_layers, d_in, rank)).astype(np.float32) * 0.02,
        "B": rng.standard_normal(
        (cfg.num_hidden_layers, rank, d_out)).astype(np.float32) * 0.02}
        for proj, (d_in, d_out) in adapter_dims_from_config(cfg).items()}


def make_adapter_engine_factory(model):
    def make_engine():
        reg = AdapterRegistry(config=model.config, max_rank=4, pool_slots=4)
        for i, aid in enumerate(ADAPTER_IDS):
            reg.add(aid, adapter_source(model.config, i))
        return InferenceEngine(model, adapter_registry=reg, **ENG_KW)
    return make_engine


class TestFleetUsage:
    def test_fleet_fold_report_agreement_and_double_bill(self, model, tmp_path,
                                                         monkeypatch):
        from paddlenlp_tpu.serving.router import launch_fleet

        ledger_dir = tmp_path / "ledger"
        monkeypatch.setenv(ENV_DIR, str(ledger_dir))
        fleet = launch_fleet(
            2, make_adapter_engine_factory(model), policy="least_loaded",
            router_registry=MetricsRegistry(), poll_interval_s=0.2,
            scheduler_config=SchedulerConfig(max_inflight=16))
        try:
            port = fleet.router_port
            jobs = [("acme", "ad-a"), ("acme", None), ("globex", "ad-b"),
                    ("globex", "ad-a"), ("initech", None), ("initech", "ad-b")]
            for i, (tenant, adapter) in enumerate(jobs):
                payload = {"prompt": [3 + i, 4, 5, 6, 7], "max_tokens": 4,
                           "tenant": tenant}
                if adapter is not None:
                    payload["adapter_id"] = adapter
                status, doc = post_json(port, "/v1/completions", payload)
                assert status == 200, doc

            # --- router fold: per-tenant + per-adapter across both replicas
            status, fold = get_json(port, "/fleet/usage")
            assert status == 200
            assert fold["tier"] == "router"
            assert fold["skipped"] == []
            assert len(fold["replicas"]) == 2
            fleet_agg = fold["fleet"]
            assert fleet_agg["records"] == len(jobs)
            assert {t: b["records"] for t, b in fleet_agg["tenants"].items()} \
                == {"acme": 2, "globex": 2, "initech": 2}
            assert {a: b["records"] for a, b in fleet_agg["adapters"].items()} \
                == {"ad-a": 2, "ad-b": 2, "base": 2}
            # adapter-slot residency is only billed to real adapter requests
            assert fleet_agg["adapters"]["ad-a"]["adapter_slot_seconds"] > 0
            assert fleet_agg["adapters"]["base"]["adapter_slot_seconds"] == 0

            # the device-side truth the offline reconciliation runs against
            status, eff = get_json(port, "/debug/efficiency")
            assert status == 200
            fleet_useful = eff["fleet"]["useful_tokens"]

            # --- adapter fan-out: one router call reaches every replica
            status, doc = post_json(port, "/admin/adapters", {"op": "list"})
            assert status == 200
            assert doc["skipped"] == [] and doc["failed"] == []
            assert len(doc["ok"]) == 2
            for out in doc["replicas"].values():
                assert out["ok"] and out["response"]["adapters"] \
                    == sorted(ADAPTER_IDS)
            # a replica-side rejection is reported per replica, still 200
            status, doc = post_json(port, "/admin/adapters",
                                    {"op": "unload", "adapter_id": "nope"})
            assert status == 200
            assert len(doc["failed"]) == 2 and doc["ok"] == []
            assert all(out["status"] == 404 for out in doc["replicas"].values())
        finally:
            fleet.shutdown(drain_timeout_s=10)

        # --- offline report over the sealed ledgers matches the live fold
        code = usage_report.main([str(ledger_dir), "--json",
                                  "--useful-total", str(fleet_useful)])
        assert code == 0
        # main prints the json doc; recompute instead of capturing stdout
        records, report = load_ledger_dir(str(ledger_dir))
        assert report["open_segments"] == 0  # shutdown sealed everything
        kept, counts, conflicts = usage_report.dedup_records(records)
        assert counts == {"unique": len(jobs), "identical_duplicates": 0,
                          "failover_superseded": 0, "conflicts": 0}
        offline = usage_report.aggregate(kept)
        for key in ("tenants", "adapters"):
            assert set(offline[key]) == set(fleet_agg[key])
            for name, bucket in offline[key].items():
                for f in ("records", "prompt_tokens", "cached_tokens",
                          "completion_tokens", "useful_tokens"):
                    assert bucket[f] == fleet_agg[key][name][f], (key, name, f)
        # metered useful vs goodput counters: exact, zero slack
        assert offline["totals"]["useful_tokens"] == fleet_useful
        assert usage_report.reconcile(offline, [fleet_useful], 0.0)["ok"]

        # --- hand-corrupt: duplicate one success with doubled tokens -> rc 1
        victim = dict(records[0])
        for f in ("prompt_tokens", "completion_tokens"):
            victim[f] = victim[f] * 2
        with open(ledger_dir / "usage-evil-000000.jsonl", "w",
                  encoding="utf-8") as f:
            f.write(json.dumps(victim) + "\n")
        assert usage_report.main([str(ledger_dir)]) == 1
