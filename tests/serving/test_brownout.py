"""Brownout ladder + overload-admission unit tests.

Controller-level: ladder escalation/de-escalation with hysteresis, the push
floor + TTL, and per-level decision surface (shed / spec-disable / clamp).
Scheduler-level (real tiny engine behind a real EngineLoop): priority-shed
ordering, deadline-aware reject-on-arrival, the queue-wait-driven Retry-After
hint tracking queue depth, and the /admin/brownout + /health HTTP contract.
"""

import http.client
import json
import time

import pytest

from paddlenlp_tpu.experimental import InferenceEngine
from paddlenlp_tpu.serving import (
    BrownoutController,
    BrownoutPolicy,
    MetricsRegistry,
    Scheduler,
    SchedulerConfig,
    ServingServer,
)
from paddlenlp_tpu.serving.scheduler import (
    DeadlineUnmetError,
    SaturatedError,
    ShedError,
    ShuttingDownError,
)
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# ---------------------------------------------------------------- controller
def make_controller(pressure, **policy_kw):
    state = {"p": pressure}
    policy = BrownoutPolicy(**{**dict(step_hold_s=1.0, exit_hold_s=2.0), **policy_kw})
    ctl = BrownoutController(policy=policy, pressure_fn=lambda: state["p"])
    return ctl, state


class TestControllerLadder:
    def test_escalates_one_level_per_hold_window(self):
        ctl, state = make_controller(2.0)
        assert ctl.evaluate(now=100.0) == 1
        # inside step_hold_s: no second escalation yet
        assert ctl.evaluate(now=100.5) == 1
        assert ctl.evaluate(now=101.1) == 2
        assert ctl.evaluate(now=102.2) == 3
        # max_level clamps
        assert ctl.evaluate(now=103.3) == 3

    def test_exit_needs_sustained_calm_per_level(self):
        ctl, state = make_controller(2.0)
        ctl.evaluate(now=100.0)
        ctl.evaluate(now=101.1)
        assert ctl.level == 2
        state["p"] = 0.1
        assert ctl.evaluate(now=102.0) == 2  # calm clock starts
        assert ctl.evaluate(now=103.0) == 2  # 1s < exit_hold 2s
        assert ctl.evaluate(now=104.1) == 1  # one step down
        assert ctl.evaluate(now=105.0) == 1  # clock restarted per level
        assert ctl.evaluate(now=106.2) == 0

    def test_flapping_pressure_never_exits(self):
        """Exit hysteresis: pressure bouncing into the band resets the calm
        clock — the ladder holds instead of oscillating."""
        ctl, state = make_controller(2.0)
        ctl.evaluate(now=100.0)
        assert ctl.level == 1
        for i in range(10):
            state["p"] = 0.1 if i % 2 == 0 else 0.8  # calm / inside band
            ctl.evaluate(now=101.0 + i)
        assert ctl.level == 1  # never exited, never escalated

    def test_push_floors_level_with_ttl(self):
        ctl, _state = make_controller(0.0)
        assert ctl.push(2, now=100.0, ttl_s=10.0) == 2
        assert ctl._effective_level(105.0) == 2  # floor active within the TTL
        assert ctl.spec_disabled(now=105.0)  # decision surface sees the floor
        # effective level falls back once the TTL lapses
        assert ctl._effective_level(111.0) == 0
        # refresh extends
        ctl.push(1, now=111.0, ttl_s=10.0)
        assert ctl._effective_level(120.0) == 1

    def test_decision_surface_per_level(self):
        ctl, state = make_controller(2.0, max_tokens_cap=8)
        now = 100.0
        assert not ctl.should_shed("best_effort", now=now)
        ctl.evaluate(now=now)
        assert ctl.should_shed("best_effort", now=now)
        assert not ctl.should_shed("interactive", now=now)
        assert not ctl.should_shed("batch", now=now)
        assert not ctl.spec_disabled(now=now)
        ctl.evaluate(now=now + 1.1)  # level 2
        assert ctl.spec_disabled(now=now + 1.1)
        assert ctl.max_tokens_cap(now=now + 1.1) is None
        ctl.evaluate(now=now + 2.2)  # level 3
        assert ctl.max_tokens_cap(now=now + 2.2) == 8

    def test_ttl_expiry_fires_exit_hook_on_next_evaluate(self):
        """A floor lapsing via TTL between calls must still fire the exit
        transition on the next evaluate() — otherwise on_level_change side
        effects (spec decode off) would outlive the brownout silently."""
        seen = []
        ctl = BrownoutController(policy=BrownoutPolicy(),
                                 pressure_fn=lambda: 0.0,
                                 on_level_change=seen.append)
        ctl.push(2, now=100.0, ttl_s=5.0)
        assert seen == [2]
        assert ctl.evaluate(now=106.0) == 0  # floor expired at 105
        assert seen == [2, 0]

    def test_level_changes_fire_hook_and_stats(self):
        seen = []
        ctl = BrownoutController(
            policy=BrownoutPolicy(step_hold_s=1.0, exit_hold_s=1.0),
            pressure_fn=lambda: 2.0, on_level_change=seen.append)
        ctl.evaluate(now=100.0)
        ctl.evaluate(now=101.1)
        assert seen == [1, 2]
        st = ctl.stats()
        assert st["level"] == 2 and st["entries"] == 1


# ---------------------------------------------------------------- scheduler-level
@pytest.fixture(scope="module")
def server():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    model = LlamaForCausalLM.from_config(cfg, seed=0)
    engine = InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=256,
                             max_blocks_per_seq=32, decode_steps=4)
    srv = ServingServer(engine, registry=MetricsRegistry(),
                        scheduler_config=SchedulerConfig(max_inflight=8))
    port = srv.start_in_thread()
    yield srv, port
    srv.shutdown(drain_timeout_s=5)


def post_json(port, path, payload):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def seed_queue_wait(loop, per_slot, n=9):
    """Seed the live queue-wait estimator (samples + freshness stamp — stale
    samples are dropped by queue_wait_estimate)."""
    loop._queue_wait_samples.extend([per_slot] * n)
    loop._qw_fresh_t = time.time()


def get_json(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


class TestPriorityShedOverHTTP:
    def test_pushed_brownout_sheds_best_effort_only(self, server):
        srv, port = server
        status, _h, doc = post_json(port, "/admin/brownout",
                                    {"level": 1, "reason": "slo_fast_burn",
                                     "ttl_s": 60.0})
        assert status == 200 and doc["level"] >= 1
        try:
            # best_effort sheds with a clean 503 + Retry-After
            status, headers, doc = post_json(port, "/v1/completions", {
                "prompt": [5, 6, 7], "max_tokens": 4, "priority": "best_effort"})
            assert status == 503
            assert doc["error"]["type"] == "overloaded_shed"
            assert int(headers["Retry-After"]) >= 1
            # interactive and batch keep flowing
            for prio in ("interactive", "batch"):
                status, _h, doc = post_json(port, "/v1/completions", {
                    "prompt": [5, 6, 7], "max_tokens": 4, "priority": prio})
                assert status == 200, (prio, doc)
                assert len(doc["choices"][0]["token_ids"]) == 4
            # the shed is visible on /health and in the metrics plane
            _s, health = get_json(port, "/health")
            assert health["brownout"] >= 1
            assert health["scheduler"]["rejected_shed"] == 1
            assert srv.loop.metrics.shed.value(reason="shed", priority="best_effort", tenant="default") == 1.0
        finally:
            post_json(port, "/admin/brownout", {"level": 0})
        assert srv.scheduler.brownout.level == 0

    def test_level2_disables_spec_decode_and_restores(self, server):
        srv, port = server
        baseline = srv.loop.engine.use_speculative
        post_json(port, "/admin/brownout", {"level": 2, "ttl_s": 60.0})
        try:
            assert srv.loop.engine.use_speculative is False
        finally:
            post_json(port, "/admin/brownout", {"level": 0})
        assert srv.loop.engine.use_speculative == baseline

    def test_level3_clamps_max_tokens(self, server):
        srv, port = server
        post_json(port, "/admin/brownout", {"level": 3, "ttl_s": 60.0})
        try:
            status, _h, doc = post_json(port, "/v1/completions", {
                "prompt": [5, 6, 7], "max_tokens": 64, "priority": "interactive"})
            assert status == 200
            cap = srv.scheduler.brownout.policy.max_tokens_cap
            assert len(doc["choices"][0]["token_ids"]) == cap
        finally:
            post_json(port, "/admin/brownout", {"level": 0})

    def test_invalid_priority_and_brownout_payloads_400(self, server):
        _srv, port = server
        status, _h, doc = post_json(port, "/v1/completions", {
            "prompt": [5, 6, 7], "max_tokens": 4, "priority": "urgent"})
        assert status == 400 and doc["error"]["type"] == "invalid_request"
        status, _h, _doc = post_json(port, "/v1/completions", {
            "prompt": [5, 6, 7], "max_tokens": 4, "deadline_ms": -5})
        assert status == 400
        status, _h, _doc = post_json(port, "/admin/brownout", {"level": 9})
        assert status == 400
        status, _h, _doc = post_json(port, "/admin/brownout", {"level": "junk"})
        assert status == 400


class TestDeadlineAdmission:
    def test_deadline_reject_on_arrival_tracks_estimate(self, server):
        srv, port = server
        loop = srv.loop
        # seed the estimator with known per-slot waits and a deep fake backlog
        seed_queue_wait(loop, 0.2)
        try:
            est = loop.queue_wait_estimate(backlog=9)
            assert est == pytest.approx(2.0)
            # a deadline under the estimate rejects on arrival
            with pytest.raises(DeadlineUnmetError) as ei:
                srv.scheduler.submit([5, 6, 7], deadline_s=0.001)
            # generous deadline admits (engine is idle: live backlog ~0)
            handle = srv.scheduler.submit([5, 6, 7], deadline_s=60.0)
            handle.result(timeout=60)
            assert ei.value.retry_after_s > 0
            assert srv.scheduler.rejected_deadline == 1
        finally:
            loop._queue_wait_samples.clear()

    def test_deadline_over_http_maps_503_with_retry_after(self, server):
        srv, port = server
        seed_queue_wait(srv.loop, 5.0)
        try:
            status, headers, doc = post_json(port, "/v1/completions", {
                "prompt": [5, 6, 7], "max_tokens": 4, "deadline_ms": 1.0})
            assert status == 503
            assert doc["error"]["type"] == "deadline_unmet"
            assert int(headers["Retry-After"]) >= 1
        finally:
            srv.loop._queue_wait_samples.clear()


class TestRetryAfterTracksQueueDepth:
    def test_estimate_scales_with_backlog(self, server):
        srv, _port = server
        loop = srv.loop
        seed_queue_wait(loop, 0.1)
        try:
            shallow = loop.queue_wait_estimate(backlog=1)
            deep = loop.queue_wait_estimate(backlog=19)
            assert deep == pytest.approx(10 * shallow)
            assert deep == pytest.approx(2.0)
        finally:
            loop._queue_wait_samples.clear()

    def test_saturated_retry_after_hint_tracks_queue_depth(self, server):
        """Satellite contract: the 429 hint is the LIVE estimate, so a deeper
        engine backlog quotes a longer backoff — not a fixed constant."""
        srv, _port = server
        sched = srv.scheduler
        loop = srv.loop
        seed_queue_wait(loop, 0.5)
        # force the window shut so submit raises SaturatedError immediately
        with sched._lock:
            saved, sched._inflight = sched._inflight, sched.config.max_inflight
        try:
            import unittest.mock as mock

            with mock.patch.object(loop, "_engine_backlog", return_value=1):
                with pytest.raises(SaturatedError) as shallow:
                    sched.submit([5, 6, 7])
            with mock.patch.object(loop, "_engine_backlog", return_value=15):
                with pytest.raises(SaturatedError) as deep:
                    sched.submit([5, 6, 7])
            assert deep.value.retry_after_s == pytest.approx(
                8 * shallow.value.retry_after_s)
        finally:
            with sched._lock:
                sched._inflight = saved
            loop._queue_wait_samples.clear()

    def test_stale_samples_expire_instead_of_latching(self, server):
        """A frozen-high estimate from a past overload must not latch
        shedding/deadline rejection forever on an idle replica: samples with
        no finish for queue_wait_sample_ttl_s fall back to the default."""
        srv, _port = server
        loop = srv.loop
        seed_queue_wait(loop, 5.0)
        assert loop.queue_wait_estimate(backlog=0) == pytest.approx(5.0)
        loop._qw_fresh_t -= loop.queue_wait_sample_ttl_s + 1  # age the ring
        assert loop.queue_wait_estimate(backlog=0) == pytest.approx(
            loop._default_queue_wait_s)
        assert not loop._queue_wait_samples  # dropped, not just ignored

    def test_estimator_feeds_from_finished_requests(self, server):
        """The sample ring fills from real finished requests' attribution."""
        srv, port = server
        before = len(srv.loop._queue_wait_samples)
        status, _h, _doc = post_json(port, "/v1/completions", {
            "prompt": [9, 8, 7], "max_tokens": 4})
        assert status == 200
        deadline = time.time() + 5
        while len(srv.loop._queue_wait_samples) <= before and time.time() < deadline:
            time.sleep(0.02)
        assert len(srv.loop._queue_wait_samples) > before


class TestDrainingBeatsBrownout:
    def test_draining_replica_reports_draining_not_shed(self, server):
        """Availability checks outrank overload controls: a draining replica
        must answer with the draining 503 (the signal the router's failure
        classification keys on), not a brownout shed — and drain-induced
        occupancy must not walk the brownout ladder."""
        srv, _port = server
        sched = Scheduler(srv.loop, SchedulerConfig(max_inflight=8))
        sched.brownout.push(1, ttl_s=60.0)
        sched.start_drain()
        with pytest.raises(ShuttingDownError):
            sched.submit([5, 6, 7], priority="best_effort")
        assert sched.rejected_shed == 0 and sched.rejected_draining == 1


class TestShedFaultPoint:
    def test_injected_shed_fault_maps_to_clean_500(self, server):
        srv, port = server
        post_json(port, "/admin/brownout", {"level": 1, "ttl_s": 60.0})
        FAULTS.arm("sched.shed", times=1)
        try:
            status, _h, _doc = post_json(port, "/v1/completions", {
                "prompt": [5, 6, 7], "max_tokens": 4, "priority": "best_effort"})
            assert status == 500
            # no admission-window slot leaked
            assert srv.scheduler.inflight == 0
            # the NEXT best_effort submission sheds normally (fault consumed)
            status, _h, doc = post_json(port, "/v1/completions", {
                "prompt": [5, 6, 7], "max_tokens": 4, "priority": "best_effort"})
            assert status == 503 and doc["error"]["type"] == "overloaded_shed"
        finally:
            post_json(port, "/admin/brownout", {"level": 0})


class TestEnginePriorityOrder:
    def test_waiting_queue_orders_by_priority_class(self):
        from paddlenlp_tpu.experimental.engine import _PRIORITY_RANK

        assert _PRIORITY_RANK == {"interactive": 0, "batch": 1, "best_effort": 2}
        cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=256,
                          eos_token_id=None, pad_token_id=0, use_scan_layers=True)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        engine = InferenceEngine(model, max_batch_size=2, block_size=4,
                                 num_blocks=64, max_blocks_per_seq=16)
        engine.add_request([5, 6, 7], priority="best_effort")
        engine.add_request([5, 6, 8], priority="batch")
        engine.add_request([5, 6, 9])  # interactive default
        engine.add_request([5, 6, 10], priority="batch")
        engine.add_request([5, 6, 11], priority="interactive")
        order = [r.priority for r in engine.waiting]
        assert order == ["interactive", "interactive", "batch", "batch",
                         "best_effort"]
        # FIFO within a class
        prompts = [int(r.prompt_ids[-1]) for r in engine.waiting]
        assert prompts == [9, 11, 8, 10, 7]
