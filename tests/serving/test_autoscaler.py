"""Autoscaler policy-loop unit tests: decisions driven by synthetic
observations against a stub admin plane / provisioner — no engines, no HTTP.

Covers the damping contracts the chaos test can't isolate: hysteresis (an
oscillating signal never flaps the fleet), cooldown spacing, the
max-envelope hold + brownout handoff, min-envelope repair, DOWN replacement,
and provision-failure retry with backoff (the tombstoned-replica guarantee).
"""

import pytest

from paddlenlp_tpu.serving import MetricsRegistry
from paddlenlp_tpu.serving.router.autoscaler import (
    Autoscaler,
    AutoscalerPolicy,
    FleetObservation,
    ProvisionedReplica,
    ReplicaObservation,
    ReplicaProvisioner,
)
from paddlenlp_tpu.utils.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


class StubAdmin:
    """Records admin-plane calls; drains complete instantly."""

    def __init__(self):
        self.added = []
        self.drained = []
        self.removed = []
        self.brownout_pushes = []
        self.fail_add = False

    def list_replicas(self):
        return {"replicas": []}

    def slo(self):
        return {"windows": {}}

    def add_replica(self, host, port):
        if self.fail_add:
            raise RuntimeError("join refused")
        self.added.append((host, port))
        return {"replica": {"id": f"{host}:{port}"}}

    def drain_replica(self, replica_id, deadline_s):
        self.drained.append(replica_id)
        return {"drain": {"id": replica_id}}

    def remove_replica(self, replica_id, force=False):
        self.removed.append((replica_id, force))
        return {"replica": {"id": replica_id}}

    def push_brownout(self, host, port, level, reason="slo_fast_burn", ttl_s=None):
        self.brownout_pushes.append((host, port, level))
        return True


class StubProvisioner(ReplicaProvisioner):
    def __init__(self):
        self.provisioned = []
        self.deprovisioned = []
        self.fail_next = 0

    def provision(self):
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError("provider quota")
        port = 9000 + len(self.provisioned)
        self.provisioned.append(port)
        return ProvisionedReplica("127.0.0.1", port)

    def deprovision(self, host, port):
        self.deprovisioned.append((host, port))


def replica(rid, state="healthy", kv=0.1, queue=0.0, draining=False,
            drained=False):
    return ReplicaObservation(id=rid, state=state, draining=draining,
                              drained=drained,
                              kv_utilization=kv, queue_depth=queue,
                              host="127.0.0.1", port=int(rid.split(":")[-1]))


def fleet_obs(n=2, kv=0.1, queue=0.0, burn=0.0, down_ids=()):
    reps = [replica(f"127.0.0.1:{8000 + i}",
                    state="down" if f"127.0.0.1:{8000 + i}" in down_ids else "healthy",
                    kv=kv, queue=queue) for i in range(n)]
    return FleetObservation(replicas=reps, availability_burn=burn, ttft_burn=0.0)


def make_scaler(policy=None, admin=None, prov=None):
    admin = admin or StubAdmin()
    prov = prov or StubProvisioner()
    scaler = Autoscaler(admin, prov, policy=policy or AutoscalerPolicy(
        min_replicas=1, max_replicas=4, hysteresis_up=2, hysteresis_down=3,
        cooldown_up_s=10.0, cooldown_down_s=20.0, max_step_up=1,
        scale_up_queue_depth=4.0, scale_down_queue_depth=0.5),
        registry=MetricsRegistry())
    return scaler, admin, prov


def actions_of(summary, kind):
    return [d for a, d in summary["actions"] if a == kind]


class TestScaleUp:
    def test_sustained_overload_scales_up_after_hysteresis(self):
        scaler, admin, prov = make_scaler()
        hot = fleet_obs(n=2, queue=8.0)
        s1 = scaler.evaluate_once(now=100.0, observation=hot)
        assert not actions_of(s1, "up")  # streak 1 < hysteresis 2
        assert actions_of(s1, "hold") == [{"reason": "hysteresis"}]
        s2 = scaler.evaluate_once(now=101.0, observation=hot)
        assert actions_of(s2, "up") == [{"added": 1, "target": 3}]
        assert admin.added == [("127.0.0.1", 9000)]
        assert prov.provisioned == [9000]

    def test_oscillating_signal_never_scales(self):
        """Hysteresis: a signal flapping hot/cold on alternate ticks resets
        the streak — the fleet never moves, in either direction."""
        scaler, admin, prov = make_scaler()
        hot = fleet_obs(n=2, queue=8.0)
        cold = fleet_obs(n=2, queue=0.0)
        for i in range(12):
            scaler.evaluate_once(now=100.0 + i,
                                 observation=hot if i % 2 == 0 else cold)
        assert admin.added == []
        assert admin.drained == []
        assert prov.provisioned == []

    def test_cooldown_spaces_scale_ups(self):
        scaler, admin, _ = make_scaler()
        hot = fleet_obs(n=2, queue=8.0)
        scaler.evaluate_once(now=100.0, observation=hot)
        s = scaler.evaluate_once(now=101.0, observation=hot)
        assert actions_of(s, "up")
        # still overloaded: next qualifying streak lands inside the cooldown
        obs3 = fleet_obs(n=3, queue=8.0)
        scaler.evaluate_once(now=102.0, observation=obs3)
        s4 = scaler.evaluate_once(now=103.0, observation=obs3)
        assert not actions_of(s4, "up")
        assert {"reason": "cooldown"} in actions_of(s4, "hold")
        # past the cooldown the same pressure scales again
        s5 = scaler.evaluate_once(now=112.0, observation=obs3)
        assert actions_of(s5, "up")

    def test_burn_rate_alone_triggers_scale_up(self):
        scaler, admin, _ = make_scaler()
        burning = fleet_obs(n=2, queue=0.0, burn=25.0)
        scaler.evaluate_once(now=100.0, observation=burning)
        s = scaler.evaluate_once(now=101.0, observation=burning)
        assert actions_of(s, "up")

    def test_max_envelope_hold_hands_off_to_brownout(self):
        policy = AutoscalerPolicy(min_replicas=1, max_replicas=2,
                                  hysteresis_up=1, brownout_push_level=1)
        scaler, admin, _ = make_scaler(policy=policy)
        pinned = fleet_obs(n=2, queue=9.0)
        s = scaler.evaluate_once(now=100.0, observation=pinned)
        assert not actions_of(s, "up")
        assert {"reason": "max_envelope"} in actions_of(s, "hold")
        # brownout handoff: every live replica got the floor
        assert len(admin.brownout_pushes) == 2
        assert all(level == 1 for _h, _p, level in admin.brownout_pushes)
        assert actions_of(s, "brownout_push")
        # the hold event dedupes per episode, the push refreshes per tick
        s2 = scaler.evaluate_once(now=101.0, observation=pinned)
        assert len(admin.brownout_pushes) == 4
        holds = [e for _t, a, _d in scaler.events if a == "hold"
                 for e in [_d] if e.get("reason") == "max_envelope"]
        assert len(holds) == 1


class TestScaleDown:
    def test_sustained_calm_scales_down_least_loaded(self):
        scaler, admin, prov = make_scaler()
        reps = [replica("127.0.0.1:8000", kv=0.2, queue=0.0),
                replica("127.0.0.1:8001", kv=0.05, queue=0.0)]
        calm = FleetObservation(replicas=reps)
        for i in range(2):
            s = scaler.evaluate_once(now=100.0 + i, observation=calm)
            assert not actions_of(s, "down")
        s = scaler.evaluate_once(now=102.0, observation=calm)
        assert actions_of(s, "down") == [{"removed": 1, "target": 1}]
        assert admin.drained == ["127.0.0.1:8001"]  # least loaded drains
        # the drain is finalized on a LATER tick — this one never blocks
        assert admin.removed == []
        done = FleetObservation(replicas=[
            reps[0], replica("127.0.0.1:8001", kv=0.05, queue=0.0,
                             draining=True, drained=True)])
        s = scaler.evaluate_once(now=102.5, observation=done)
        assert admin.removed == [("127.0.0.1:8001", False)]
        assert prov.deprovisioned == [("127.0.0.1", 8001)]
        assert actions_of(s, "drained") == [
            {"replica": "127.0.0.1:8001", "forced": False}]

    def test_stuck_drain_force_removed_at_deadline(self):
        scaler, admin, prov = make_scaler()
        reps = [replica("127.0.0.1:8000", kv=0.2, queue=0.0),
                replica("127.0.0.1:8001", kv=0.05, queue=0.0)]
        calm = FleetObservation(replicas=reps)
        for i in range(3):
            scaler.evaluate_once(now=100.0 + i, observation=calm)
        assert admin.drained == ["127.0.0.1:8001"]
        # the victim keeps reporting not-drained (a wedged stream): pending
        # until the drain deadline, then force-removed — never stranded
        stuck = FleetObservation(replicas=[
            reps[0], replica("127.0.0.1:8001", kv=0.05, queue=0.0,
                             draining=True)])
        s = scaler.evaluate_once(now=110.0, observation=stuck)
        assert not actions_of(s, "drained")
        deadline = 102.0 + scaler.policy.drain_deadline_s + 10.0
        s = scaler.evaluate_once(now=deadline + 1.0, observation=stuck)
        assert admin.removed == [("127.0.0.1:8001", True)]
        assert actions_of(s, "drained") == [
            {"replica": "127.0.0.1:8001", "forced": True}]
        assert prov.deprovisioned == [("127.0.0.1", 8001)]

    def test_never_below_min_envelope(self):
        scaler, admin, _ = make_scaler()
        calm = fleet_obs(n=1, queue=0.0)
        for i in range(6):
            scaler.evaluate_once(now=100.0 + i, observation=calm)
        assert admin.drained == []
        assert any(a == "hold" and d.get("reason") == "min_envelope"
                   for _t, a, d in scaler.events)


class TestReplaceAndRepair:
    def test_down_replica_replaced_without_hysteresis(self):
        scaler, admin, prov = make_scaler()
        obs = fleet_obs(n=2, down_ids=("127.0.0.1:8001",))
        s = scaler.evaluate_once(now=100.0, observation=obs)
        assert actions_of(s, "replace") == [{"replica": "127.0.0.1:8001"}]
        assert ("127.0.0.1:8001", True) in admin.removed  # forced
        # the replacement provisioned on the same tick
        assert prov.provisioned == [9000]
        assert admin.added == [("127.0.0.1", 9000)]
        assert s["deficit"] == 0

    def test_failed_provision_retries_with_backoff(self):
        """The tombstoned-replica guarantee: a DOWN replica whose replacement
        provision fails stays OWED — retried after backoff, never forgotten."""
        scaler, admin, prov = make_scaler()
        prov.fail_next = 2
        obs = fleet_obs(n=2, down_ids=("127.0.0.1:8001",))
        s = scaler.evaluate_once(now=100.0, observation=obs)
        assert s["deficit"] == 1  # provision failed, debt recorded
        assert scaler.metrics.provision_failures.value() == 1.0
        # inside the backoff window: held, not retried
        healthy = fleet_obs(n=1)
        s2 = scaler.evaluate_once(now=100.1, observation=healthy)
        assert s2["deficit"] == 1
        assert {"reason": "provision_backoff"} in actions_of(s2, "hold")
        # past the backoff: retried (fails once more, backoff doubles)
        s3 = scaler.evaluate_once(now=101.0, observation=healthy)
        assert s3["deficit"] == 1
        # and eventually succeeds
        s4 = scaler.evaluate_once(now=103.0, observation=healthy)
        assert s4["deficit"] == 0
        assert prov.provisioned == [9000]
        assert admin.added == [("127.0.0.1", 9000)]

    def test_failed_join_tears_down_orphan(self):
        scaler, admin, prov = make_scaler()
        admin.fail_add = True
        obs = fleet_obs(n=1, down_ids=("127.0.0.1:8000",))
        s = scaler.evaluate_once(now=100.0, observation=obs)
        assert s["deficit"] >= 1
        # the provisioned-but-unjoined replica was torn back down
        assert prov.deprovisioned[-1] == ("127.0.0.1", 9000)

    def test_injected_provision_fault_is_retried(self):
        """router.provision fault point: an injected failure behaves exactly
        like a provider error — backoff + retry, no strand."""
        FAULTS.arm("router.provision", times=1)
        scaler, admin, prov = make_scaler()
        obs = fleet_obs(n=2, down_ids=("127.0.0.1:8001",))
        s = scaler.evaluate_once(now=100.0, observation=obs)
        assert s["deficit"] == 1
        assert prov.provisioned == []  # fault fired BEFORE the provider call
        s2 = scaler.evaluate_once(now=102.0, observation=fleet_obs(n=1))
        assert s2["deficit"] == 0
        assert prov.provisioned == [9000]


class TestObservationParsing:
    def test_observe_folds_admin_planes(self):
        class Admin(StubAdmin):
            def list_replicas(self):
                return {"replicas": [
                    {"id": "a", "state": "healthy", "draining": False,
                     "kv_utilization": 0.5, "queue_depth": 3,
                     "host": "127.0.0.1", "port": 8000},
                    {"id": "b", "state": "down", "draining": True,
                     "kv_utilization": None, "queue_depth": 0,
                     "host": "127.0.0.1", "port": 8001},
                ]}

            def slo(self):
                return {"windows": {
                    "60s": {"availability_burn_rate": 2.5, "ttft_burn_rate": 7.0},
                    "300s": {"availability_burn_rate": 99.0, "ttft_burn_rate": 99.0},
                }}

        scaler, _admin, _prov = make_scaler(admin=Admin())
        obs = scaler.observe()
        assert [r.id for r in obs.replicas] == ["a", "b"]
        assert obs.replicas[1].draining is True
        assert obs.replicas[1].kv_utilization == 0.0  # None -> 0.0
        # the SHORTEST window's burns are the fast signal
        assert obs.availability_burn == 2.5
        assert obs.ttft_burn == 7.0
