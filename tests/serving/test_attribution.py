"""Per-request latency attribution (ISSUE 13): every finished request's e2e
latency decomposes into queue / admission_gate / prefill / chunk_stall /
migration_wait / decode phases that (a) sum to e2e within 5%, (b) agree with
the pre-existing queue_wait/ttft/decode_time request fields, (c) land in the
`paddlenlp_serving_latency_attribution_seconds{phase}` histogram family and
on GET /debug/requests. Also covers the /debug/requests kv_stage +
migration-wait-so-far fix (disagg visibility) and the flight recorder's
zero-cost disabled path at engine-step level."""

import http.client
import json
import time

import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.observability import RECORDER
from paddlenlp_tpu.serving import MetricsRegistry, SchedulerConfig, ServingServer
from paddlenlp_tpu.serving.engine_loop import ATTRIBUTION_PHASES, request_attribution
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(autouse=True)
def _clean_recorder():
    RECORDER.clear()
    RECORDER.set_enabled(True)
    yield
    RECORDER.clear()
    RECORDER.set_enabled(True)


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=8,
                      max_position_embeddings=256, eos_token_id=None, pad_token_id=0,
                      use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


@pytest.fixture(scope="module")
def server_port(model):
    engine = InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=256,
                             max_blocks_per_seq=32, decode_steps=4,
                             prefill_chunk_tokens=8)
    server = ServingServer(engine, registry=MetricsRegistry(),
                           scheduler_config=SchedulerConfig(max_inflight=16))
    port = server.start_in_thread()
    yield server, port
    server.shutdown(drain_timeout_s=10)


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, json.loads(body)


def _complete(port, prompt, max_tokens=8):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/completions",
                 body=json.dumps({"prompt": prompt, "max_tokens": max_tokens}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = json.loads(resp.read())
    conn.close()
    assert resp.status == 200, out
    return out


class TestAttributionParity:
    def test_phases_sum_to_e2e_and_match_request_fields(self, server_port):
        """Acceptance: for every finished request the phases sum to e2e
        within 5%, verified against queue_wait/ttft/decode_time."""
        server, port = server_port
        for i in range(6):
            _complete(port, [5 + i, 6, 7, 8, 9, 10, 11, 12, 13, 14], max_tokens=6)
        _, doc = _get(port, "/debug/requests")
        rows = [r for r in doc["recent"] if r["finish_reason"] in ("stop", "length")]
        assert len(rows) >= 6
        for row in rows:
            attr = row["attribution"]
            assert set(attr) == set(ATTRIBUTION_PHASES)
            assert all(v >= 0 for v in attr.values()), attr
            e2e = row["finish_t"] - row["arrival_t"]
            assert abs(sum(attr.values()) - e2e) <= 0.05 * e2e + 1e-6, (attr, e2e)
            # parity with the pre-existing request timing fields
            assert attr["queue"] + attr["admission_gate"] == \
                pytest.approx(row["queue_wait_s"], rel=0.05, abs=1e-6)
            assert attr["prefill"] == \
                pytest.approx(row["ttft_s"] - row["queue_wait_s"], rel=0.05, abs=1e-6)
            assert attr["chunk_stall"] + attr["migration_wait"] + attr["decode"] == \
                pytest.approx(row["decode_time_s"], rel=0.05, abs=1e-6)

    def test_histogram_family_and_debug_requests(self, server_port):
        server, port = server_port
        _complete(port, [40, 41, 42], max_tokens=4)
        hist = server.registry.get("paddlenlp_serving_latency_attribution_seconds")
        n_finished = server.registry.get(
            "paddlenlp_serving_requests_total").value(status="length", priority="interactive", tenant="default")
        for phase in ATTRIBUTION_PHASES:
            # one observation per phase per finished request
            assert hist.count(phase=phase) == n_finished, phase
        # the per-phase sums reconstruct the e2e sum (histogram-level parity)
        e2e_sum = server.registry.get("paddlenlp_serving_e2e_seconds").sum()
        attr_sum = sum(hist.sum(phase=p) for p in ATTRIBUTION_PHASES)
        assert attr_sum == pytest.approx(e2e_sum, rel=0.05)

    def test_decision_trail_recorded_per_request(self, server_port):
        server, port = server_port
        RECORDER.clear()
        _complete(port, [60, 61, 62, 63, 64, 65, 66, 67, 68, 69], max_tokens=4)
        _, doc = _get(port, "/debug/requests")
        trace = doc["recent"][-1]["trace"]
        names = [e.name for e in RECORDER.snapshot(trace=trace)]
        assert "admit.accept" in names
        # a 10-token prompt through chunk budget 8 takes >= 2 chunk grants
        assert names.count("chunk.grant") >= 2


class TestChunkStallAttribution:
    def test_decode_rows_riding_chunk_steps_accumulate_stall(self, model):
        """Deterministic engine-level check: a decoding request sharing mixed
        steps with another request's prefill chunks accrues chunk_stall."""
        eng = InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=128,
                              max_blocks_per_seq=32, decode_steps=4,
                              prefill_chunk_tokens=4)
        a = eng.add_request([5, 6, 7], SamplingParams(max_new_tokens=24))
        eng.step()  # admit A; first chunk
        while not any(r is not None and r.req_id == a and r.output_ids
                      for r in eng.slots):
            eng.step()
        req_a = next(r for r in eng.slots if r.req_id == a)
        assert req_a.chunk_stall_s == 0.0  # nothing else prefilled yet
        eng.add_request(list(range(20, 44)), SamplingParams(max_new_tokens=2))
        finished = []
        while eng.has_work():
            finished.extend(eng.step())
        done_a = next(r for r in finished if r.req_id == a)
        assert done_a.chunk_stall_s > 0.0  # B's 24-token prefill rode A's decode steps
        attr = request_attribution(done_a)
        assert attr["chunk_stall"] == pytest.approx(
            min(done_a.chunk_stall_s, done_a.decode_time), rel=1e-6)
        e2e = done_a.finish_t - done_a.arrival_t
        assert sum(attr.values()) == pytest.approx(e2e, rel=1e-9)


class TestDisaggAttribution:
    @pytest.fixture(scope="class")
    def disagg_engine(self, model, eight_devices):
        return InferenceEngine(model, disagg_stages=(1, 1), max_batch_size=4,
                               block_size=4, num_blocks=128, max_blocks_per_seq=32,
                               decode_steps=4)

    def test_migration_wait_attributed(self, disagg_engine):
        eng = disagg_engine
        rid = eng.add_request([5, 6, 7, 8], SamplingParams(max_new_tokens=6))
        finished = []
        while eng.has_work():
            finished.extend(eng.step())
        req = next(r for r in finished if r.req_id == rid)
        assert req.migration_wait_s > 0.0  # prefill->decode handoff waited >= 1 poll
        assert req.migrate_start_t is None  # episode closed on land
        attr = request_attribution(req)
        assert attr["migration_wait"] == pytest.approx(
            min(req.migration_wait_s, req.decode_time), rel=1e-6)
        assert sum(attr.values()) == pytest.approx(
            req.finish_t - req.arrival_t, rel=1e-9)
        # the decision trail names the handoff
        names = [e.name for e in RECORDER.snapshot(req_id=rid)]
        assert "migrate.start" in names and "migrate.land" in names

    def test_debug_requests_surfaces_kv_stage_and_migration_wait(self, model,
                                                                 eight_devices):
        """Satellite fix: /debug/requests on a disagg engine shows
        Request.kv_stage and migration-wait-so-far for in-flight requests."""
        engine = InferenceEngine(model, disagg_stages=(1, 1), max_batch_size=4,
                                 block_size=4, num_blocks=128, max_blocks_per_seq=32,
                                 decode_steps=4)
        server = ServingServer(engine, registry=MetricsRegistry(),
                               scheduler_config=SchedulerConfig(max_inflight=8))
        port = server.start_in_thread()
        try:
            handle = server.scheduler.submit(
                [5, 6, 7, 8], SamplingParams(max_new_tokens=100), timeout_s=60)
            seen = None
            deadline = time.time() + 30
            while time.time() < deadline and not handle.done():
                _, doc = _get(port, "/debug/requests")
                rows = [r for r in doc["inflight"] if "kv_stage" in r]
                if rows:
                    seen = rows[0]
                    break
                time.sleep(0.005)
            assert seen is not None, "request never surfaced kv_stage"
            assert seen["kv_stage"] in ("prefill", "migrating", "decode")
            assert seen["migration_wait_s"] >= 0.0
        finally:
            server.scheduler.cancel(handle)
            handle.result(timeout=30)
            server.shutdown(drain_timeout_s=10)


class TestRecorderDisabledAtEngineLevel:
    def test_disabled_recorder_records_nothing_per_step(self, model):
        """Satellite 6 at engine level: with PDNLP_TPU_FLIGHT_RECORDER off,
        a full serve cycle (admissions, chunks, decode steps) records zero
        events — and steady-state decode steps hit no recorder call sites at
        all even when enabled."""
        eng = InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=128,
                              max_blocks_per_seq=32, decode_steps=4,
                              prefill_chunk_tokens=8)
        RECORDER.clear()
        RECORDER.set_enabled(False)
        try:
            eng.generate([[5, 6, 7, 8] * 3, [9, 10, 11]],
                         SamplingParams(max_new_tokens=8))
            assert len(RECORDER) == 0 and RECORDER.dropped == 0
        finally:
            RECORDER.set_enabled(True)
        # enabled, steady-state decode: admission already done, no chunks, no
        # migrations -> an engine step crosses zero decision edges
        rid = eng.add_request([30, 31, 32], SamplingParams(max_new_tokens=32))
        eng.step()  # admission + chunks land here
        while next(r for r in eng.slots if r.req_id == rid).needs_prefill:
            eng.step()
        RECORDER.clear()
        for _ in range(4):
            eng.step()
        assert len(RECORDER) == 0  # pure decode steps record nothing
        eng.abort(rid)
