"""Batched multi-LoRA token identity + the multi-tenant serving plane (ISSUE 16).

The load-bearing property: a request decoding in a MIXED batch — rows on
three different adapters and a base-model row, all in one jitted step — must
produce bitwise the tokens of an uncontended solo run. Greedy, seeded
sampling and penalties; and the identity must survive the chunked-prefill x
prefix-cache x tensor-parallel matrix. The prefix cache is keyed
``(adapter_id, tokens)``: base KV must never warm an adapter's prompt or
vice versa.

HTTP side: ``POST /admin/adapters`` hot-load/unload/list, per-tenant
``max_inflight`` quota (429 while other tenants admit), and tenant-labeled
metrics + per-tenant goodput.

CPU-only, tiny model — tier-1 speed."""

import http.client
import json
import threading

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import MetricsRegistry, SchedulerConfig, ServingServer
from paddlenlp_tpu.serving.tenancy import AdapterRegistry, TenantQuotas
from paddlenlp_tpu.serving.tenancy.adapters import adapter_dims_from_config
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.safetensors_io import save_file

ENG_KW = dict(max_batch_size=4, block_size=4, num_blocks=128,
              max_blocks_per_seq=32, decode_steps=4)
ADAPTER_IDS = ("ad-a", "ad-b", "ad-c")
GEN = 12
#: four mixed rows: three adapters + one base-model row, prompts long enough
#: (12 tokens) that an 8-token prefill chunk actually splits them
JOBS = [([3 + j, 7, 11, 2, 9, 4, 8, 6, 5, 10, 12, 13 + j], aid)
        for j, aid in enumerate([*ADAPTER_IDS, None])]


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def adapter_source(cfg, idx, rank=4):
    rng = np.random.default_rng(1000 + idx)
    return {proj: {"A": rng.standard_normal((cfg.num_hidden_layers, d_in, rank)).astype(np.float32) * 0.02,
                   "B": rng.standard_normal((cfg.num_hidden_layers, rank, d_out)).astype(np.float32) * 0.02}
            for proj, (d_in, d_out) in adapter_dims_from_config(cfg).items()}


def make_registry(cfg, pool_slots=4):
    reg = AdapterRegistry(config=cfg, max_rank=4, pool_slots=pool_slots)
    for i, aid in enumerate(ADAPTER_IDS):
        reg.add(aid, adapter_source(cfg, i))
    return reg


def run_jobs(eng, jobs, sampling):
    """Submit every job, then drain — rows decode batched together."""
    ids = [eng.add_request(list(p), sampling, adapter_id=aid) for p, aid in jobs]
    done = {}
    while eng.has_work():
        for req in eng.step():
            done[req.req_id] = req
    return [done[i].output_ids for i in ids]


def solo(model, job, sampling, **eng_kw):
    """One-request run on a fresh engine + registry: the identity reference."""
    kw = dict(ENG_KW, **eng_kw)
    eng = InferenceEngine(model, adapter_registry=make_registry(model.config), **kw)
    return run_jobs(eng, [job], sampling)[0]


GREEDY = SamplingParams(max_new_tokens=GEN)
SAMPLED = SamplingParams(max_new_tokens=GEN, do_sample=True, temperature=0.8,
                         top_p=0.9, top_k=8, seed=7, repetition_penalty=1.2,
                         presence_penalty=0.1, frequency_penalty=0.1)


class TestBatchedIdentity:
    @pytest.mark.parametrize("sampling", [GREEDY, SAMPLED],
                             ids=["greedy", "sampled_penalties"])
    def test_mixed_batch_bitwise_equals_solo(self, model, sampling):
        eng = InferenceEngine(model, adapter_registry=make_registry(model.config),
                              **ENG_KW)
        batched = run_jobs(eng, JOBS, sampling)
        for (prompt, aid), got in zip(JOBS, batched):
            assert len(got) == GEN
            np.testing.assert_array_equal(
                got, solo(model, (prompt, aid), sampling),
                err_msg=f"adapter={aid}")

    def test_adapters_actually_steer(self, model):
        """The deltas are live: with deltas strong enough to flip argmax,
        every adapter's output differs from base and from each other (guards
        against a silently-zero gather)."""
        cfg = model.config
        reg = AdapterRegistry(config=cfg, max_rank=4, pool_slots=4)
        for i, aid in enumerate(ADAPTER_IDS):
            reg.add(aid, adapter_source(cfg, i), scaling=40.0)
        eng = InferenceEngine(model, adapter_registry=reg, **ENG_KW)
        prompt = [5, 6, 7, 8, 9, 10, 11, 12]
        outs = run_jobs(eng, [(prompt, aid) for aid in (*ADAPTER_IDS, None)],
                        SamplingParams(max_new_tokens=16))
        seen = {tuple(o) for o in outs}
        assert len(seen) == 4, "some adapter produced base-model tokens"


class TestExecutionMatrix:
    """Chunked prefill x prefix cache x tensor parallel: every cell's mixed
    batch must match the PLAIN single-device engine's solo tokens bitwise —
    the stronger form of identity (the matrix features are exact
    transformations, not approximations)."""

    @pytest.fixture(scope="class")
    def reference(self, model):
        return [solo(model, job, GREEDY) for job in JOBS]

    @pytest.mark.parametrize("eng_kw", [
        dict(prefill_chunk_tokens=8),
        dict(mesh_shape=(1, 2)),
        dict(mesh_shape=(1, 2), prefill_chunk_tokens=8),
        dict(mesh_shape=(1, 2), prefill_chunk_tokens=8,
             enable_prefix_cache=False),
    ], ids=["chunked", "tp2", "tp2_chunked", "tp2_chunked_nocache"])
    def test_cell_matches_plain_solo(self, model, reference, eng_kw):
        eng = InferenceEngine(model, adapter_registry=make_registry(model.config),
                              **dict(ENG_KW, **eng_kw))
        batched = run_jobs(eng, JOBS, GREEDY)
        for (prompt, aid), got, want in zip(JOBS, batched, reference):
            np.testing.assert_array_equal(
                got, want, err_msg=f"adapter={aid} cell={eng_kw}")


class TestPrefixCacheSalting:
    def test_cache_keyed_by_adapter_id(self, model):
        """Same prompt, different adapter => no cache reuse; same prompt,
        same adapter => warm hit with identical tokens."""
        eng = InferenceEngine(model, adapter_registry=make_registry(model.config),
                              **ENG_KW)
        prompt = [3, 7, 11, 2, 9, 4, 8, 6, 5, 10, 12, 13]  # 3 full blocks
        first = run_jobs(eng, [(prompt, "ad-a")], GREEDY)[0]
        assert eng.mgr.cache_hits == 0

        # base-model rerun of the SAME prompt: the ad-a KV (base+delta
        # product) must not serve it — and the tokens must be pure base
        base = run_jobs(eng, [(prompt, None)], GREEDY)[0]
        assert eng.mgr.cache_hits == 0, "adapter KV leaked into a base request"
        np.testing.assert_array_equal(base, solo(model, (prompt, None), GREEDY))
        assert base != first

        # cross-adapter rerun: ad-b must not reuse ad-a's blocks either
        run_jobs(eng, [(prompt, "ad-b")], GREEDY)
        assert eng.mgr.cache_hits == 0, "adapter KV leaked across adapters"

        # same-adapter rerun: NOW the cache engages, tokens unchanged
        again = run_jobs(eng, [(prompt, "ad-a")], GREEDY)[0]
        assert eng.mgr.cache_hits == 1
        np.testing.assert_array_equal(again, first)


def post(port, path, payload, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


class TestServingPlane:
    def test_admin_adapters_hot_load_unload(self, model, tmp_path):
        cfg = model.config
        srv = ServingServer(
            InferenceEngine(model, adapter_registry=make_registry(cfg), **ENG_KW),
            scheduler_config=SchedulerConfig(max_inflight=8, default_timeout_s=600.0),
            registry=MetricsRegistry())
        port = srv.start_in_thread()
        try:
            status, doc = post(port, "/admin/adapters", {"op": "list"})
            assert status == 200 and doc["adapters"] == sorted(ADAPTER_IDS)

            # unknown adapter on a completion: the door check answers 400
            # with the registered ids, before anything is admitted
            status, doc = post(port, "/v1/completions",
                               {"prompt": [5, 6, 7], "max_tokens": 2,
                                "adapter_id": "nope"})
            assert status == 400 and "ad-a" in doc["error"]["message"]

            # hot-load a 4th adapter from an export-format safetensors file
            src = adapter_source(cfg, 9)
            path = str(tmp_path / "ad-new.safetensors")
            save_file({f"{proj}.{m}": w["A"] if m == "lora_A" else w["B"]
                       for proj, w in src.items() for m in ("lora_A", "lora_B")},
                      path, metadata={"format": "np", "scaling": "1.0"})
            status, doc = post(port, "/admin/adapters",
                               {"op": "load", "adapter_id": "ad-new", "path": path})
            assert status == 200 and "ad-new" in doc["adapters"] and doc["digest"]

            # the hot-loaded adapter serves token-exact vs a solo engine that
            # registered the same weights at construction time
            status, doc = post(port, "/v1/completions",
                               {"prompt": [5, 6, 7, 8], "max_tokens": 8,
                                "adapter_id": "ad-new"})
            assert status == 200
            reg2 = make_registry(cfg)
            reg2.add("ad-new", src)
            eng2 = InferenceEngine(model, adapter_registry=reg2, **ENG_KW)
            np.testing.assert_array_equal(
                doc["choices"][0]["token_ids"],
                run_jobs(eng2, [([5, 6, 7, 8], "ad-new")],
                         SamplingParams(max_new_tokens=8))[0])

            status, doc = post(port, "/admin/adapters",
                               {"op": "unload", "adapter_id": "ad-new"})
            assert status == 200 and "ad-new" not in doc["adapters"]
            status, _ = post(port, "/admin/adapters",
                             {"op": "unload", "adapter_id": "ad-new"})
            assert status == 404
        finally:
            srv.shutdown(drain_timeout_s=5)

    def test_tenant_quota_sheds_only_the_capped_tenant(self, model):
        metrics = MetricsRegistry()
        srv = ServingServer(
            InferenceEngine(model, adapter_registry=make_registry(model.config),
                            **ENG_KW),
            scheduler_config=SchedulerConfig(max_inflight=8, default_timeout_s=600.0),
            tenant_quotas=TenantQuotas({"acme": {"max_inflight": 1}}),
            registry=metrics)
        port = srv.start_in_thread()
        try:
            first_token = threading.Event()
            long_result = {}

            def long_stream():
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
                conn.request("POST", "/v1/completions",
                             body=json.dumps({"prompt": [5, 6, 7], "max_tokens": 32,
                                              "stream": True, "tenant": "acme",
                                              "adapter_id": "ad-a"}),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                long_result["status"] = resp.status
                toks = []
                while True:
                    line = resp.readline()
                    if not line or line.strip() == b"data: [DONE]":
                        break
                    line = line.strip()
                    if line.startswith(b"data: "):
                        c = json.loads(line[len(b"data: "):])["choices"][0]
                        if "token" in c:
                            toks.append(c["token"])
                            first_token.set()
                conn.close()
                long_result["tokens"] = toks

            t = threading.Thread(target=long_stream)
            t.start()
            assert first_token.wait(timeout=120)

            # acme is at its 1-inflight cap: shed with 429 + Retry-After...
            status, doc = post(port, "/v1/completions",
                               {"prompt": [8, 9], "max_tokens": 2, "tenant": "acme"})
            assert status == 429, doc
            assert doc["error"]["type"] == "rate_limit_exceeded"
            # ...while an uncapped tenant admits normally, same instant
            status, doc = post(port, "/v1/completions",
                               {"prompt": [8, 9], "max_tokens": 2, "tenant": "globex"})
            assert status == 200, doc

            t.join(timeout=300)
            assert long_result["status"] == 200 and len(long_result["tokens"]) == 32

            # cap releases with the stream: acme admits again
            status, _ = post(port, "/v1/completions",
                             {"prompt": [8, 9], "max_tokens": 2, "tenant": "acme"})
            assert status == 200

            # tenant-labeled accounting on both counters
            text = metrics.expose()
            assert ('paddlenlp_serving_requests_shed_total{reason="tenant_quota",'
                    'priority="interactive",tenant="acme"}') in text
            assert ('paddlenlp_serving_requests_total{status="length",'
                    'priority="interactive",tenant="globex"}') in text
            assert srv.scheduler.stats()["rejected_tenant_quota"] >= 1

            # per-tenant goodput fold rides engine stats
            tenancy = srv.loop.engine.stats()["tenancy"]
            assert "acme" in tenancy["tenants"] and "globex" in tenancy["tenants"]
            assert tenancy["tenants"]["acme"]["tokens_out"] >= 32
            assert tenancy["adapters"]["registered"] == 3
        finally:
            srv.shutdown(drain_timeout_s=5)
