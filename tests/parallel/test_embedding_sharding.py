"""VocabEmbed sharding regression: under tp the compiled train step must never
all-gather the full embedding table (the round-1 "involuntary full
rematerialization" on the embed_tokens gather), and the one-hot matmul lookup
must be numerically identical to the plain gather."""

import re

import jax
import jax.numpy as jnp
import numpy as np

from paddlenlp_tpu.parallel import MeshConfig, create_mesh, use_mesh
from paddlenlp_tpu.parallel.partition import shard_params
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

VOCAB, HIDDEN = 256, 64


def tiny(seed=0):
    cfg = LlamaConfig(
        vocab_size=VOCAB, hidden_size=HIDDEN, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=128,
    )
    return LlamaForCausalLM.from_config(cfg, seed=seed)


def test_no_full_table_allgather_under_tp(eight_devices):
    model = tiny()
    mesh = create_mesh(MeshConfig(fsdp=2, cp=2, tp=2))
    rules = model.get_partition_rules()
    ids = jnp.ones((4, 32), jnp.int32)

    with use_mesh(mesh):
        params = shard_params(model.params, rules, mesh)

        def loss_fn(p, ids):
            logits = model.module.apply({"params": p}, input_ids=ids, deterministic=True).logits
            return logits.astype(jnp.float32).mean()

        step = jax.jit(jax.grad(loss_fn))
        text = step.lower(params, ids).compile().as_text()

    # every all-gather result shape must be smaller than the full [V, E] table
    sizes = []
    for m in re.finditer(r"all-gather[.\d]*\s*=\s*\(?\s*(\w+)\[([\d,]+)\]", text):
        dims = [int(d) for d in m.group(2).split(",") if d]
        sizes.append(int(np.prod(dims)) if dims else 0)
    assert sizes, "expected some all-gathers under fsdp/tp"
    assert max(sizes) < VOCAB * HIDDEN, f"full embedding table all-gathered: {sorted(sizes)[-4:]}"


def test_onehot_lookup_parity_with_gather(eight_devices):
    model = tiny()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, VOCAB, (2, 16)), jnp.int32)
    plain = model(input_ids=ids).logits  # off-mesh: take path

    mesh = create_mesh(MeshConfig(tp=2))
    rules = model.get_partition_rules()
    with use_mesh(mesh):
        params = shard_params(model.params, rules, mesh)
        sharded = jax.jit(
            lambda p, i: model.module.apply({"params": p}, input_ids=i, deterministic=True).logits
        )(params, ids)
    np.testing.assert_allclose(np.asarray(plain), np.asarray(sharded), atol=2e-5, rtol=2e-5)
