"""2-process multihost training: spawn two jax.distributed CPU processes (4
virtual devices each -> one 8-device global mesh) and require loss parity with
the same config run single-process on 8 devices.

Counterpart of the reference's subprocess cluster simulator
(tests/parallel_launch.py:171, run_n2c4 two-simulated-nodes mode) +
test_unified_checkpoint's loss checks."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
WORKER = os.path.join(REPO, "tests", "parallel", "multihost_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_loss_parity(tmp_path, eight_devices):
    port = _free_port()
    out_file = str(tmp_path / "losses.json")
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            PDNLP_COORDINATOR=f"127.0.0.1:{port}",
            PDNLP_NUM_PROCESSES="2",
            PDNLP_PROCESS_ID=str(pid),
            PDNLP_TEST_OUT=out_file,
            PDNLP_TEST_DIR=str(tmp_path / f"w{pid}"),
        )
        procs.append(subprocess.Popen([sys.executable, WORKER], env=env,
                                      stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    multi = json.load(open(out_file))

    # single-process reference on the IN-PROCESS 8-device mesh, same config/data
    from paddlenlp_tpu.trainer import Trainer, TrainingArguments
    from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
    from tests.parallel.multihost_worker import make_dataset, metric_fn

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
    )
    model = LlamaForCausalLM.from_config(cfg, seed=0)
    args = TrainingArguments(
        output_dir=str(tmp_path / "single"), max_steps=3, per_device_train_batch_size=2,
        gradient_accumulation_steps=2, learning_rate=1e-3, logging_steps=1, save_strategy="no",
        tensor_parallel_degree=2, sharding="stage3", sharding_parallel_degree=2,
        seed=0, data_seed=11,
    )
    trainer = Trainer(model=model, args=args, train_dataset=make_dataset(),
                      eval_dataset=make_dataset(n=20), compute_metrics=metric_fn)
    trainer.train()
    single = [h["loss"] for h in trainer.state.log_history if "loss" in h]
    assert len(multi["losses"]) == len(single) == 3
    np.testing.assert_allclose(multi["losses"], single, rtol=1e-4, atol=1e-4)

    # eval metrics + predict must now be gathered on multihost and agree with
    # the single-process values (the multihost path gathers the device-sharded
    # logits; the single-process path reads them off-device directly)
    eval_metrics = trainer.evaluate()
    np.testing.assert_allclose(multi["eval_loss"], eval_metrics["eval_loss"], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(multi["eval_checksum"], eval_metrics["eval_pred_checksum"],
                               rtol=1e-4, atol=1e-5)
    pred = trainer.predict(make_dataset(n=20))
    real = (np.asarray(pred.label_ids) != -100).any(-1)
    pred_mean = float(np.asarray(pred.predictions, np.float64)[real].mean())
    np.testing.assert_allclose(multi["pred_mean"], pred_mean, rtol=1e-4, atol=1e-5)
