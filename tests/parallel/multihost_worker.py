"""Worker process for the 2-process multihost trainer test.

Launched by tests/parallel/test_multihost.py with PDNLP_* env vars (the launch
contract of parallel/launch.py); each process owns 4 virtual CPU devices, the
global mesh is dp2 x fsdp2 x tp2 over 8 devices. Process 0 writes its per-step
losses to the path in PDNLP_TEST_OUT.

Counterpart of the reference's local-subprocess cluster simulator
(tests/parallel_launch.py:171 TestMultipleGpus / run_n2c4). Import-safe: all
jax/distributed setup happens only under __main__ (the test imports
``make_dataset`` from this module).
"""

import json
import os
import sys

import numpy as np


def make_dataset(n=64, seq=16):
    rng = np.random.default_rng(7)
    rows = [rng.integers(0, 128, size=seq).astype(np.int32) for _ in range(n)]

    class DS:
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {"input_ids": rows[i], "labels": rows[i].copy()}

    return DS()


def metric_fn(p):
    """Deterministic checksum over REAL rows only (filler rows — wrap-padded on
    multihost, row-0 repeats single-host — carry all-(-100) labels) so the two
    paths compare over the identical sample set."""
    real = (np.asarray(p.label_ids) != -100).any(-1)
    return {"pred_checksum": float(np.asarray(p.predictions, np.float64)[real].mean())}


def main():
    import jax

    from paddlenlp_tpu.trainer import Trainer, TrainingArguments
    from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
    )
    model = LlamaForCausalLM.from_config(cfg, seed=0)
    args = TrainingArguments(
        output_dir=os.environ.get("PDNLP_TEST_DIR", "/tmp/mh_out"),
        max_steps=3, per_device_train_batch_size=2, gradient_accumulation_steps=2,
        learning_rate=1e-3, logging_steps=1, save_strategy="no",
        tensor_parallel_degree=2, sharding="stage3", sharding_parallel_degree=2,
        seed=0, data_seed=11,
    )
    trainer = Trainer(model=model, args=args, train_dataset=make_dataset(),
                      eval_dataset=make_dataset(n=20), compute_metrics=metric_fn)
    trainer.train()
    losses = [h["loss"] for h in trainer.state.log_history if "loss" in h]
    # multihost evaluate()/predict() gather metrics across processes
    # (reference trainer.py:2911 evaluation_loop gathers across ranks)
    eval_metrics = trainer.evaluate()
    pred = trainer.predict(make_dataset(n=20))
    real = (np.asarray(pred.label_ids) != -100).any(-1)
    pred_mean = float(np.asarray(pred.predictions, np.float64)[real].mean())
    if jax.process_index() == 0:
        with open(os.environ["PDNLP_TEST_OUT"], "w") as f:
            json.dump({"losses": losses,
                       "eval_checksum": eval_metrics["eval_pred_checksum"],
                       "eval_loss": eval_metrics["eval_loss"],
                       "pred_mean": pred_mean}, f)
    print(f"worker {jax.process_index()} done: {losses}")


if __name__ == "__main__":
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)

    from paddlenlp_tpu.parallel.launch import init_distributed

    assert init_distributed(), "multihost init failed"
    main()
