"""Pipeline parallelism tests: the spatial microbatch pipeline must be a pure
re-scheduling — same math as running the layer stack sequentially, and a pp2
trainer must reproduce pp1 losses at the same global batch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.parallel import MeshConfig, create_mesh, use_mesh
from paddlenlp_tpu.parallel.pipeline import spatial_pipeline
from paddlenlp_tpu.trainer import Trainer, TrainingArguments
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

class TestSpatialPipeline:
    def test_matches_sequential(self, eight_devices):
        L, M, mb, D = 4, 3, 2, 8
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(L, D, D)), jnp.float32)
        b = jnp.asarray(rng.normal(size=(L, D)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(M, mb, D)), jnp.float32)

        def layer_fn(lp, state):
            h, acc = state
            h = jnp.tanh(h @ lp["w"] + lp["b"])
            return (h, acc + h.sum())

        # sequential reference
        seq_h, seq_acc = [], []
        for m in range(M):
            h, acc = x[m], jnp.zeros(())
            for l in range(L):
                (h, acc) = layer_fn({"w": w[l], "b": b[l]}, (h, acc))
            seq_h.append(h)
            seq_acc.append(acc)

        mesh = create_mesh(MeshConfig(pp=2, tp=2, fsdp=2))
        with use_mesh(mesh):
            out_h, out_acc = jax.jit(
                lambda p, s: spatial_pipeline(layer_fn, p, s, n_stages=2)
            )({"w": w, "b": b}, (x, jnp.zeros((M,))))
        np.testing.assert_allclose(np.asarray(out_h), np.asarray(jnp.stack(seq_h)), atol=1e-6)
        np.testing.assert_allclose(np.asarray(out_acc), np.asarray(jnp.stack(seq_acc)), atol=1e-5)

    def test_grad_flows_through_pipeline(self, eight_devices):
        L, M, mb, D = 2, 2, 1, 4
        w = jnp.ones((L, D, D), jnp.float32) * 0.1
        x = jnp.ones((M, mb, D), jnp.float32)

        def layer_fn(lp, h):
            return jnp.tanh(h @ lp)

        def loss(w):
            out = spatial_pipeline(layer_fn, w, x, n_stages=2)
            return (out**2).sum()

        def loss_seq(w):
            outs = []
            for m in range(M):
                h = x[m]
                for l in range(L):
                    h = layer_fn(w[l], h)
                outs.append(h)
            return (jnp.stack(outs) ** 2).sum()

        mesh = create_mesh(MeshConfig(pp=2))
        with use_mesh(mesh):
            g = jax.jit(jax.grad(loss))(w)
        g_ref = jax.grad(loss_seq)(w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-6)

def _data(n=64, seq=16):
    rng = np.random.default_rng(7)
    rows = [rng.integers(0, 128, size=seq).astype(np.int32) for _ in range(n)]

    class DS:
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {"input_ids": rows[i], "labels": rows[i].copy()}

    return DS()

def _run(tmp_path, tag, *, pp, tp, mbs, steps=2):
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
    )
    model = LlamaForCausalLM.from_config(cfg, seed=0)
    args = TrainingArguments(
        output_dir=str(tmp_path / tag), max_steps=steps, per_device_train_batch_size=mbs,
        gradient_accumulation_steps=4, learning_rate=1e-3, logging_steps=1,
        save_strategy="no", tensor_parallel_degree=tp, pipeline_parallel_degree=pp,
        seed=0, data_seed=11,
    )
    trainer = Trainer(model=model, args=args, train_dataset=_data())
    trainer.train()
    return [h["loss"] for h in trainer.state.log_history if "loss" in h]

class TestPipelineTrainerParity:
    def test_pp2_matches_pp1(self, tmp_path, eight_devices):
        # identical global batch (32): pp1tp2 -> 4 data shards x mbs2 x accum4;
        # pp2tp2 -> 2 data shards x mbs4 x accum4 (accum axis = microbatches)
        base = _run(tmp_path, "pp1", pp=1, tp=2, mbs=2)
        piped = _run(tmp_path, "pp2", pp=2, tp=2, mbs=4)
        assert len(base) == len(piped) >= 2
        np.testing.assert_allclose(base, piped, rtol=2e-4, atol=2e-4)

class TestPipelineDropout:
    def test_dropout_threads_through_pipeline(self, eight_devices):
        """With attention_dropout on, the pipelined loss must (a) be stochastic
        across rng keys, (b) be reproducible for the same key, and (c) match the
        deterministic loss when the rng is withheld — i.e. dropout actually
        reaches the layers instead of being silently ignored (round-2 weak item)."""
        cfg = LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=4,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            attention_dropout=0.5, use_scan_layers=True,
        )
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        rng = np.random.default_rng(3)
        ids = jnp.asarray(rng.integers(0, 128, size=(2, 2, 16)), jnp.int32)  # [M, mb, T]
        batch = {"input_ids": ids, "labels": ids.copy()}
        mesh = create_mesh(MeshConfig(pp=2, tp=2, fsdp=2))
        with use_mesh(mesh):
            fn = jax.jit(lambda p, key: model.pipelined_loss(p, batch, n_stages=2, dropout_rng=key))
            det_fn = jax.jit(lambda p: model.pipelined_loss(p, batch, n_stages=2, dropout_rng=None))
            l1 = float(fn(model.params, jax.random.key(0)))
            l1_again = float(fn(model.params, jax.random.key(0)))
            l2 = float(fn(model.params, jax.random.key(1)))
            det = float(det_fn(model.params))
        assert l1 == l1_again  # same key -> bit-stable
        assert l1 != l2, "dropout rng has no effect in the pipeline"
        assert det not in (l1, l2) and np.isfinite(det)

class TestPPVocabSharding:
    def test_embed_and_head_shard_over_pp(self, tmp_path, eight_devices):
        """pp>1 must NOT replicate the embedding/lm_head per stage: the vocab
        dim rides (tp, pp) so each stage holds 1/(tp*pp) of both tables."""

        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                          num_hidden_layers=4, num_attention_heads=2, num_key_value_heads=2,
                          max_position_embeddings=64, use_scan_layers=True)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        data = [{"input_ids": np.asarray([3, 4, 5, 6, 7, 8], np.int32),
                 "labels": np.asarray([4, 5, 6, 7, 8, 9], np.int32)} for _ in range(32)]
        args = TrainingArguments(output_dir=str(tmp_path), per_device_train_batch_size=4,
                                 max_steps=1, pipeline_parallel_degree=2,
                                 tensor_parallel_degree=2, logging_steps=100)
        trainer = Trainer(model=model, args=args, train_dataset=data)
        trainer.create_optimizer_and_scheduler(num_training_steps=1)
        state = trainer._make_train_state()
        embed = state.params["model"]["embed_tokens"]["embedding"]
        head = state.params["lm_head"]["kernel"]
        assert "pp" in str(embed.sharding.spec) and "tp" in str(embed.sharding.spec), embed.sharding
        assert "pp" in str(head.sharding.spec), head.sharding
        # vocab dim split across tp*pp=4: each shard holds 128/4 rows
        shard_shape = embed.sharding.shard_shape(embed.shape)
        assert shard_shape[0] == 128 // 4, shard_shape
