"""UIE information-extraction taskflow + SimpleServer REST round-trips
(reference: paddlenlp/taskflow/information_extraction.py, paddlenlp/server/)."""

import json
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(scope="module")
def uie_dir(tmp_path_factory):
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    from paddlenlp_tpu.transformers import PretrainedTokenizer
    from paddlenlp_tpu.transformers.ernie.configuration import ErnieConfig
    from paddlenlp_tpu.transformers.ernie.modeling import UIE

    root = tmp_path_factory.mktemp("uie")
    vocab = {"<pad>": 0, "<unk>": 1}
    for i, w in enumerate("alice works at acme corp person company of the".split()):
        vocab[w] = i + 2
    t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    t.pre_tokenizer = Whitespace()
    PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", unk_token="<unk>").save_pretrained(str(root))
    cfg = ErnieConfig(vocab_size=16, hidden_size=32, num_hidden_layers=1, num_attention_heads=2,
                      intermediate_size=64, max_position_embeddings=64)
    UIE.from_config(cfg, seed=0).save_pretrained(str(root))
    return str(root)


def _force_heads(task_model, fire: bool):
    """Pin the pointer heads: kernel=0, bias=+/-10 -> prob ~ 1 or ~ 0."""
    b = 10.0 if fire else -10.0
    p = dict(task_model.params)
    for head in ("linear_start", "linear_end"):
        h = dict(p[head])
        h["kernel"] = jnp.zeros_like(h["kernel"])
        h["bias"] = jnp.full_like(h["bias"], b)
        p[head] = h
    task_model.params = p


class TestUIETask:
    def test_all_fire_extracts_every_text_token(self, uie_dir):
        from paddlenlp_tpu.taskflow import Taskflow

        flow = Taskflow("information_extraction", task_path=uie_dir, schema="person")
        _force_heads(flow.task._model, fire=True)
        text = "alice works at acme"
        out = flow(text)
        assert set(out) == {"person"}
        spans = out["person"]
        # every TEXT token (never the prompt) extracted as a single-token span
        assert [s["text"] for s in spans] == text.split()
        for s in spans:
            assert text[s["start"]:s["end"]] == s["text"]
            assert 0.99 < s["probability"] <= 1.0

    def test_no_fire_returns_empty(self, uie_dir):
        from paddlenlp_tpu.taskflow import Taskflow

        flow = Taskflow("information_extraction", task_path=uie_dir, schema=["person", "company"])
        _force_heads(flow.task._model, fire=False)
        out = flow(["alice works at acme", "acme corp"])
        assert out == [{}, {}]

    def test_nested_schema_attaches_relations(self, uie_dir):
        from paddlenlp_tpu.taskflow import Taskflow

        flow = Taskflow("information_extraction", task_path=uie_dir,
                        schema={"person": ["company"]})
        _force_heads(flow.task._model, fire=True)
        out = flow("alice works")
        assert "person" in out
        for span in out["person"]:
            assert "relations" in span
            assert "company" in span["relations"]
            assert all(r["text"] for r in span["relations"]["company"])

    def test_schema_required(self, uie_dir):
        from paddlenlp_tpu.taskflow import Taskflow

        flow = Taskflow("information_extraction", task_path=uie_dir)
        with pytest.raises(ValueError, match="schema"):
            flow("alice")


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


class TestTokenClassificationTasks:
    @pytest.fixture(scope="class")
    def ner_dir(self, tmp_path_factory):
        from tokenizers import Tokenizer
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        from paddlenlp_tpu.transformers import PretrainedTokenizer
        from paddlenlp_tpu.transformers.ernie.configuration import ErnieConfig
        from paddlenlp_tpu.transformers.ernie.modeling import ErnieForTokenClassification

        root = tmp_path_factory.mktemp("ner")
        vocab = {"<pad>": 0, "<unk>": 1}
        for i, w in enumerate("alice visited paris yesterday bob".split()):
            vocab[w] = i + 2
        t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
        t.pre_tokenizer = Whitespace()
        PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", unk_token="<unk>").save_pretrained(str(root))
        cfg = ErnieConfig(vocab_size=16, hidden_size=32, num_hidden_layers=1, num_attention_heads=2,
                          intermediate_size=64, max_position_embeddings=64, num_labels=5,
                          id2label={"0": "O", "1": "B-PER", "2": "I-PER", "3": "B-LOC", "4": "I-LOC"})
        ErnieForTokenClassification.from_config(cfg, seed=0).save_pretrained(str(root))
        return str(root)

    def test_ner_spans(self, ner_dir):
        from paddlenlp_tpu.taskflow import Taskflow

        flow = Taskflow("ner", task_path=ner_dir)
        out = flow("alice visited paris")
        assert out["text"] == "alice visited paris"
        for tag in out["tags"]:
            assert out["text"][tag["start"]:tag["end"]] == tag["token"]
            assert tag["label"] in ("O", "PER", "LOC")

    def test_word_segmentation_and_pos(self, ner_dir):
        from paddlenlp_tpu.taskflow import Taskflow

        words = Taskflow("word_segmentation", task_path=ner_dir)("alice visited paris")
        assert all(isinstance(w, str) for w in words)
        pos = Taskflow("pos_tagging", task_path=ner_dir)("alice visited paris")
        assert all(isinstance(w, str) and isinstance(l, str) for w, l in pos)


class TestSimpleServer:
    def test_taskflow_and_model_routes(self, uie_dir, tmp_path):
        from tokenizers import Tokenizer
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        from paddlenlp_tpu.server import SimpleServer
        from paddlenlp_tpu.taskflow import Taskflow
        from paddlenlp_tpu.transformers import BertConfig, BertForSequenceClassification, PretrainedTokenizer

        flow = Taskflow("information_extraction", task_path=uie_dir, schema="person")
        _force_heads(flow.task._model, fire=True)

        cls_dir = tmp_path / "cls"
        vocab = {"<pad>": 0, "<unk>": 1, "good": 2, "bad": 3}
        t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
        t.pre_tokenizer = Whitespace()
        tok = PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", unk_token="<unk>")
        cfg = BertConfig(vocab_size=8, hidden_size=32, num_hidden_layers=1, num_attention_heads=2,
                         intermediate_size=64, max_position_embeddings=32, num_labels=2,
                         id2label={"0": "negative", "1": "positive"})
        cls_model = BertForSequenceClassification.from_config(cfg, seed=0)

        server = SimpleServer()
        server.register_taskflow("uie", flow)
        server.register("cls", model_path=str(cls_dir), model=cls_model, tokenizer=tok)
        port = server.start_in_thread()
        try:
            # health
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/health") as r:
                health = json.loads(r.read())
            assert health["status"] == "ok"
            assert "/taskflow/uie" in health["routes"] and "/models/cls" in health["routes"]

            # taskflow route, schema re-target via parameters
            out = _post(port, "/taskflow/uie",
                        {"data": {"text": "alice works"}, "parameters": {"schema": "company"}})
            assert "company" in out["result"]

            # model route with labels
            out = _post(port, "/models/cls", {"data": {"text": ["good good", "bad bad"]}})
            res = out["result"]
            assert len(res["label"]) == 2
            assert all(l in ("negative", "positive") for l in res["label"])
            assert np.asarray(res["logits"]).shape == (2, 2)

            # unknown route -> 404
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(port, "/models/nope", {})
            assert e.value.code == 404
        finally:
            server.shutdown()
