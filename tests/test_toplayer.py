"""Top layer: metrics, quantization, taskflow, CLI."""

import json
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.metrics import BLEU, AccuracyAndF1, Distinct, Perplexity, Rouge1, RougeL


class TestMetrics:
    def test_bleu_perfect_and_zero(self):
        b = BLEU(2)
        b.add_inst(list("abcd"), [list("abcd")])
        assert b.score() == pytest.approx(1.0)
        b2 = BLEU(2)
        b2.add_inst(list("abcd"), [list("wxyz")])
        assert b2.score() < 1e-4

    def test_rouge(self):
        r1 = Rouge1()
        r1.add_inst(["the", "cat", "sat"], [["the", "cat", "ran"]])
        assert r1.score() == pytest.approx(2 / 3)
        rl = RougeL()
        rl.add_inst(["a", "b", "c", "d"], [["a", "b", "x", "d"]])
        assert 0 < rl.score() <= 1

    def test_perplexity_uniform(self):
        V = 8
        p = Perplexity()
        logits = np.zeros((1, 5, V))
        labels = np.array([[1, 2, 3, -100, 4]])
        p.update(logits, labels)
        assert p.accumulate() == pytest.approx(V, rel=1e-4)

    def test_accuracy_f1(self):
        m = AccuracyAndF1()
        m.update([1, 0, 1, 1], [1, 0, 0, 1])
        out = m.accumulate()
        assert out["accuracy"] == pytest.approx(0.75)
        assert out["f1"] == pytest.approx(2 * (2 / 3) * 1.0 / ((2 / 3) + 1.0))

    def test_distinct(self):
        d = Distinct(2)
        d.add_inst(["a", "b", "a", "b"])
        assert d.score() == pytest.approx(2 / 3)


class TestQuantization:
    def _model(self):
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=64, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64)
        return LlamaForCausalLM.from_config(cfg, seed=0)

    def test_wint8_roundtrip_close(self):
        from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel

        model = self._model()
        ids = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
        ref = model(input_ids=ids).logits
        qm = QuantizedModel(model, QuantizationConfig(weight_quantize_algo="wint8"))
        out = qm(input_ids=ids).logits
        # int8 weight-only: logits close, not exact
        corr = np.corrcoef(np.asarray(ref).ravel(), np.asarray(out).ravel())[0, 1]
        assert corr > 0.999, corr
        assert np.asarray(ref).argmax(-1).tolist() == np.asarray(out).argmax(-1).tolist()

    def test_wint4_and_footprint(self):
        from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel

        model = self._model()
        base_bytes = sum(np.asarray(x).nbytes for x in __import__("jax").tree.leaves(model.params))
        qm = QuantizedModel(model, QuantizationConfig(weight_quantize_algo="wint4"))
        assert qm.memory_footprint() < base_bytes * 0.6
        out = qm(input_ids=jnp.asarray([[5, 6, 7]], jnp.int32)).logits
        assert np.isfinite(np.asarray(out)).all()

    def test_unknown_algo_raises(self):
        from paddlenlp_tpu.quantization import QuantizationConfig

        with pytest.raises(ValueError, match="unsupported"):
            QuantizationConfig(weight_quantize_algo="a8w8c8")

    def test_quantized_generate(self):
        from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel

        qm = QuantizedModel(self._model(), QuantizationConfig(weight_quantize_algo="wint8"))
        out, _ = qm.generate(jnp.asarray([[5, 6, 7]], jnp.int32), max_new_tokens=4, do_sample=False)
        assert out.shape == (1, 4)


@pytest.fixture(scope="module")
def hub_dir(tmp_path_factory):
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    from paddlenlp_tpu.transformers import (
        BertConfig, BertForSequenceClassification, LlamaConfig, LlamaForCausalLM, PretrainedTokenizer,
    )

    root = tmp_path_factory.mktemp("taskflow-hub")
    vocab = {"<pad>": 0, "<s>": 1, "</s>": 2, "<unk>": 3}
    for i, w in enumerate("good bad great awful fine movie film nice happy sad".split()):
        vocab[w] = i + 4
    t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    t.pre_tokenizer = Whitespace()
    tok = PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", eos_token="</s>", unk_token="<unk>")

    gen_dir = root / "gen"
    LlamaForCausalLM.from_config(
        LlamaConfig(vocab_size=32, hidden_size=32, intermediate_size=64, num_hidden_layers=1,
                    num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64,
                    eos_token_id=2, pad_token_id=0), seed=0
    ).save_pretrained(str(gen_dir))
    tok.save_pretrained(str(gen_dir))

    cls_dir = root / "cls"
    cfg = BertConfig(vocab_size=32, hidden_size=32, num_hidden_layers=1, num_attention_heads=2,
                     intermediate_size=64, max_position_embeddings=64, num_labels=2,
                     id2label={"0": "negative", "1": "positive"})
    BertForSequenceClassification.from_config(cfg, seed=0).save_pretrained(str(cls_dir))
    tok.save_pretrained(str(cls_dir))
    return {"gen": str(gen_dir), "cls": str(cls_dir)}


class TestTaskflow:
    def test_text_generation(self, hub_dir):
        from paddlenlp_tpu.taskflow import Taskflow

        flow = Taskflow("text_generation", task_path=hub_dir["gen"], max_new_tokens=4, dtype="float32")
        out = flow("good movie")
        assert "answer" in out and isinstance(out["answer"], str)

    def test_sentiment(self, hub_dir):
        from paddlenlp_tpu.taskflow import Taskflow

        flow = Taskflow("sentiment_analysis", task_path=hub_dir["cls"], dtype="float32")
        out = flow(["good great nice", "bad awful sad"])
        assert len(out) == 2
        assert out[0]["label"] in ("negative", "positive")
        assert 0 <= out[0]["score"] <= 1

    def test_unknown_task(self):
        from paddlenlp_tpu.taskflow import Taskflow

        with pytest.raises(ValueError, match="unknown task"):
            Taskflow("time_travel")


class TestCLI:
    def test_version(self, capsys):
        from paddlenlp_tpu.cli import main

        main(["version"])
        out = json.loads(capsys.readouterr().out)
        assert "paddlenlp_tpu" in out and "jax" in out

    def test_predict(self, hub_dir, capsys):
        from paddlenlp_tpu.cli import main

        main(["predict", "--model", hub_dir["gen"], "--prompt", "good", "--max_length", "3",
              "--dtype", "float32"])
        out = json.loads(capsys.readouterr().out)
        assert "answer" in out


class TestTaskflowBreadth:
    def _mlm_dir(self, tmp_path):
        from tokenizers import Tokenizer
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        from paddlenlp_tpu.transformers import BertConfig, BertForMaskedLM, PretrainedTokenizer

        d = str(tmp_path / "mlm")
        vocab = {"<pad>": 0, "maskword": 1, "<unk>": 2}
        for i, w in enumerate("the cat sat mat dog ran good bad".split()):
            vocab[w] = i + 3
        t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
        t.pre_tokenizer = Whitespace()
        PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", mask_token="maskword",
                            unk_token="<unk>").save_pretrained(d)
        BertForMaskedLM.from_config(
            BertConfig(vocab_size=16, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                       num_attention_heads=2, max_position_embeddings=32), seed=0).save_pretrained(d)
        return d

    def test_fill_mask(self, tmp_path):
        from paddlenlp_tpu.taskflow import Taskflow

        tf = Taskflow("fill_mask", task_path=self._mlm_dir(tmp_path), top_k=3)
        out = tf("the maskword sat")
        assert len(out["candidates"]) == 3
        assert all(0 <= c["score"] <= 1 for c in out["candidates"])

    def test_question_answering_and_summarization_registered(self):
        from paddlenlp_tpu.taskflow.taskflow import TASKS, _populate

        _populate()
        for name in ("fill_mask", "question_answering", "text_summarization", "chat"):
            assert name in TASKS, name


class TestTaskflowRound5:
    """feature_extraction / zero_shot_text_classification / text_correction
    + generation-flavored aliases (reference taskflow registry breadth)."""

    def _enc_dir(self, tmp_path):
        from tokenizers import Tokenizer
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        from paddlenlp_tpu.transformers import BertConfig, BertModel, PretrainedTokenizer

        d = str(tmp_path / "enc")
        vocab = {"<pad>": 0, "<unk>": 1}
        for i, w in enumerate("sports movie politics the game team film actor vote law".split()):
            vocab[w] = i + 2
        t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
        t.pre_tokenizer = Whitespace()
        PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", unk_token="<unk>").save_pretrained(d)
        BertModel.from_config(
            BertConfig(vocab_size=16, hidden_size=32, intermediate_size=64, num_hidden_layers=1,
                       num_attention_heads=2, max_position_embeddings=32), seed=0).save_pretrained(d)
        return d

    def test_feature_extraction(self, tmp_path):
        from paddlenlp_tpu.taskflow import Taskflow

        tf = Taskflow("feature_extraction", task_path=self._enc_dir(tmp_path))
        out = tf(["the game", "the film"])
        assert out["features"].shape == (2, 32)

    def test_zero_shot_classification(self, tmp_path):
        from paddlenlp_tpu.taskflow import Taskflow

        tf = Taskflow("zero_shot_text_classification", task_path=self._enc_dir(tmp_path),
                      schema=["sports", "movie"], template="{}")
        out = tf("the team game")
        assert len(out) == 1 and len(out[0]["predictions"]) == 2
        scores = [p["score"] for p in out[0]["predictions"]]
        assert abs(sum(scores) - 1.0) < 1e-5
        assert scores == sorted(scores, reverse=True)

    def test_text_correction(self, tmp_path):
        from tokenizers import Tokenizer
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        from paddlenlp_tpu.taskflow import Taskflow
        from paddlenlp_tpu.transformers import BertConfig, BertForMaskedLM, PretrainedTokenizer

        d = str(tmp_path / "csc")
        vocab = {"<pad>": 0, "<unk>": 1}
        for i, w in enumerate("the cat sat mat dog ran".split()):
            vocab[w] = i + 2
        t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
        t.pre_tokenizer = Whitespace()
        PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", unk_token="<unk>").save_pretrained(d)
        BertForMaskedLM.from_config(
            BertConfig(vocab_size=8, hidden_size=32, intermediate_size=64, num_hidden_layers=1,
                       num_attention_heads=2, max_position_embeddings=32), seed=0).save_pretrained(d)
        tf = Taskflow("text_correction", task_path=d, threshold=1e9)  # high bar: no corrections
        out = tf("the cat sat")
        assert out[0]["errors"] == [] and out[0]["target"] == "the cat sat"

    def test_round5_tasks_registered(self):
        from paddlenlp_tpu.taskflow.taskflow import TASKS, _populate

        _populate()
        for name in ("feature_extraction", "zero_shot_text_classification", "text_correction",
                     "code_generation", "poetry_generation", "dialogue", "question_generation",
                     "lexical_analysis"):
            assert name in TASKS, name
        assert len(TASKS) >= 21
