"""PEFT tests: LoRA (init/forward/train/save-load/merge) and prefix tuning."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.peft import LoRAConfig, LoRAModel, PrefixConfig, PrefixModelForCausalLM
from paddlenlp_tpu.trainer import Trainer, TrainingArguments
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.transformers.conversion_utils import flatten_params


def tiny_model(seed=0, **kw):
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64, **kw)
    return LlamaForCausalLM.from_config(cfg, seed=seed)


class ToyDS:
    def __init__(self, n=32):
        rng = np.random.default_rng(0)
        base = rng.integers(2, 128, size=(8, 16))
        self.d = base[rng.integers(0, 8, size=n)]

    def __len__(self):
        return len(self.d)

    def __getitem__(self, i):
        ids = self.d[i].astype(np.int32)
        return {"input_ids": ids, "labels": ids.copy()}


class TestLoRA:
    def test_zero_init_is_identity(self):
        """Fresh adapters (B=0) must not change the forward."""
        model = tiny_model()
        ids = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
        base_logits = model(input_ids=ids).logits
        lora = LoRAModel(model, LoRAConfig(r=4))
        lora_logits = lora(input_ids=ids).logits
        np.testing.assert_allclose(np.asarray(base_logits), np.asarray(lora_logits), atol=1e-6)

    def test_adapters_on_scanned_kernels(self):
        model = tiny_model()
        lora = LoRAModel(model, LoRAConfig(r=4))
        flat = flatten_params(lora.params)
        a = flat["model/layers/self_attn/q_proj/lora_A"]
        assert a.shape == (2, 64, 4)  # [L, in, r] on the scanned stack

    def test_trainable_mask_and_training(self, tmp_path):
        model = tiny_model()
        lora = LoRAModel(model, LoRAConfig(r=4, lora_alpha=8))
        base_before = {p: np.asarray(v) for p, v in flatten_params(lora.params).items() if "/lora_" not in p}
        args = TrainingArguments(output_dir=str(tmp_path), max_steps=4, per_device_train_batch_size=4,
                                 learning_rate=5e-3, logging_steps=2, save_strategy="no")
        tr = Trainer(model=lora, args=args, train_dataset=ToyDS())
        out = tr.train()
        flat_after = flatten_params(tr.train_state.params)
        # base params untouched; adapters moved
        for p, before in base_before.items():
            np.testing.assert_array_equal(before, np.asarray(flat_after[p]), err_msg=p)
        moved = [p for p in flat_after if p.endswith("lora_B") and np.abs(np.asarray(flat_after[p])).sum() > 0]
        assert moved, "lora_B never updated"
        assert np.isfinite(out.training_loss)

    def test_save_load_adapters(self, tmp_path):
        model = tiny_model()
        lora = LoRAModel(model, LoRAConfig(r=4))
        # perturb adapters so load has something to verify
        flat = flatten_params(lora.params)
        for p in flat:
            if p.endswith("lora_B"):
                flat[p] = jnp.ones_like(flat[p]) * 0.01
        from paddlenlp_tpu.transformers.conversion_utils import unflatten_params

        lora.params = unflatten_params(flat)
        ids = jnp.asarray([[3, 4, 5]], jnp.int32)
        before = lora(input_ids=ids).logits
        lora.save_pretrained(str(tmp_path))
        assert os.path.isfile(tmp_path / "lora_model.safetensors")

        fresh = LoRAModel.from_pretrained(tiny_model(), str(tmp_path))
        after = fresh(input_ids=ids).logits
        np.testing.assert_allclose(np.asarray(before), np.asarray(after), atol=1e-6)

    def test_merge_and_unload(self):
        model = tiny_model()
        lora = LoRAModel(model, LoRAConfig(r=4))
        flat = flatten_params(lora.params)
        for p in flat:
            if p.endswith("lora_B"):
                flat[p] = jnp.ones_like(flat[p]) * 0.02
        from paddlenlp_tpu.transformers.conversion_utils import unflatten_params

        lora.params = unflatten_params(flat)
        ids = jnp.asarray([[3, 4, 5]], jnp.int32)
        adapted = lora(input_ids=ids).logits
        merged_model = lora.merge_and_unload()
        merged_logits = merged_model(input_ids=ids).logits
        np.testing.assert_allclose(np.asarray(adapted), np.asarray(merged_logits), atol=1e-5)

    def test_export_adapter_registry_roundtrip(self, tmp_path):
        """export_adapter() -> AdapterRegistry.add round-trip: the serving
        pool's canonical weights are exactly A and scaling-folded B, via both
        the safetensors file (scaling in metadata) and the in-memory dict."""
        from paddlenlp_tpu.serving.tenancy import AdapterRegistry

        model = tiny_model()
        lora = LoRAModel(model, LoRAConfig(r=4, lora_alpha=8))  # scaling = 2.0
        flat = flatten_params(lora.params)
        for p in flat:
            if p.endswith("lora_B"):
                flat[p] = jnp.ones_like(flat[p]) * 0.01
        from paddlenlp_tpu.transformers.conversion_utils import unflatten_params

        lora.params = unflatten_params(flat)
        path = str(tmp_path / "adapter.safetensors")
        exported = lora.export_adapter(path)
        assert exported["q_proj.lora_A"].shape == (2, 64, 4)
        assert exported["q_proj.lora_B"].shape == (2, 4, 64)
        assert exported["down_proj.lora_B"].shape == (2, 4, 64)
        assert len(exported) == 14  # 7 projections x A/B

        registry = AdapterRegistry(config=model.config, max_rank=4)
        digest = registry.add("tuned", exported, scaling=lora.lora_config.scaling)
        w = registry.weights_of("tuned")
        np.testing.assert_allclose(w["q_proj"]["A"], exported["q_proj.lora_A"], atol=0)
        np.testing.assert_allclose(  # scaling folded into B at add time
            w["q_proj"]["B"], exported["q_proj.lora_B"] * 2.0, rtol=1e-6)
        # same bytes -> same digest: re-add is an idempotent no-op
        assert registry.add("tuned", exported,
                            scaling=lora.lora_config.scaling) == digest
        # the safetensors file (scaling riding in its metadata) is an
        # equivalent add source and content-addresses to the same digest
        registry2 = AdapterRegistry(config=model.config, max_rank=4)
        assert registry2.add("from-file", path) == digest

    def test_export_adapter_stacks_unscanned_layers(self):
        model = tiny_model(use_scan_layers=False)
        lora = LoRAModel(model, LoRAConfig(r=4))
        exported = lora.export_adapter()
        # per-layer [in, r] leaves stack into the scanned [L, in, r] layout
        assert exported["q_proj.lora_A"].shape == (2, 64, 4)

    def test_generate_with_adapters(self):
        model = tiny_model()
        lora = LoRAModel(model, LoRAConfig(r=4))
        out, _ = lora.generate(jnp.asarray([[5, 6, 7]], jnp.int32), max_new_tokens=4, do_sample=False)
        assert out.shape == (1, 4)


class TestPrefix:
    def test_forward_shapes(self):
        model = tiny_model()
        pm = PrefixModelForCausalLM(model, PrefixConfig(num_prefix_tokens=8))
        out = pm(input_ids=jnp.asarray([[3, 4, 5, 6]], jnp.int32))
        assert out.logits.shape == (1, 4, 128)

    def test_prefix_changes_logits_and_trains(self, tmp_path):
        model = tiny_model()
        ids = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
        base = model(input_ids=ids).logits
        pm = PrefixModelForCausalLM(model, PrefixConfig(num_prefix_tokens=8))
        prefixed = pm(input_ids=ids).logits
        assert np.abs(np.asarray(base) - np.asarray(prefixed)).max() > 1e-6

        args = TrainingArguments(output_dir=str(tmp_path), max_steps=3, per_device_train_batch_size=4,
                                 learning_rate=1e-2, save_strategy="no", logging_steps=1)
        tr = Trainer(model=pm, args=args, train_dataset=ToyDS())
        tr.train()
        flat = flatten_params(tr.train_state.params)
        base_kernel = flat["model/layers/self_attn/q_proj/kernel"]
        np.testing.assert_array_equal(np.asarray(base_kernel),
                                      np.asarray(flatten_params(pm.params)["model/layers/self_attn/q_proj/kernel"]))

    def test_save_load(self, tmp_path):
        model = tiny_model()
        pm = PrefixModelForCausalLM(model, PrefixConfig(num_prefix_tokens=8))
        ids = jnp.asarray([[3, 4, 5]], jnp.int32)
        before = pm(input_ids=ids).logits
        pm.save_pretrained(str(tmp_path))
        fresh = PrefixModelForCausalLM.from_pretrained(tiny_model(), str(tmp_path))
        np.testing.assert_allclose(np.asarray(before), np.asarray(fresh(input_ids=ids).logits), atol=1e-6)


class TestVeRA:
    def _model(self):
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64)
        return LlamaForCausalLM.from_config(cfg, seed=0)

    def test_vera_learns_with_tiny_param_count(self, tmp_path):
        import jax
        import numpy as np

        from paddlenlp_tpu.peft import VeRAConfig, VeRAModel
        from paddlenlp_tpu.trainer import Trainer, TrainingArguments
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params

        model = self._model()
        vera = VeRAModel(model, VeRAConfig(r=8))
        flat = flatten_params(vera.params)
        trainable = sum(int(np.prod(v.shape)) for p, v in flat.items() if "/vera_" in p)
        assert 0 < trainable < 3000  # vectors only

        rows = [np.random.default_rng(1).integers(0, 64, 12).astype(np.int32) for _ in range(64)]

        class DS:
            def __len__(self):
                return 64

            def __getitem__(self, i):
                return {"input_ids": rows[i], "labels": rows[i].copy()}

        args = TrainingArguments(output_dir=str(tmp_path), max_steps=6, per_device_train_batch_size=4,
                                 learning_rate=5e-2, logging_steps=1, save_strategy="no")
        trainer = Trainer(model=vera, args=args, train_dataset=DS())
        trainer.train()
        losses = [h["loss"] for h in trainer.state.log_history if "loss" in h]
        assert losses[-1] < losses[0], losses
        # frozen leaves (incl. shared bases) must be untouched
        before = flatten_params(vera.params)
        after = flatten_params(trainer.train_state.params)
        np.testing.assert_array_equal(np.asarray(before["vera_shared/32x32/A"]),
                                      np.asarray(after["vera_shared/32x32/A"]))

    def test_vera_save_load_roundtrip(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np

        from paddlenlp_tpu.peft import VeRAConfig, VeRAModel

        model = self._model()
        vera = VeRAModel(model, VeRAConfig(r=4))
        ids = jnp.asarray([[5, 6, 7]], jnp.int32)
        ref = vera(input_ids=ids).logits
        vera.save_pretrained(str(tmp_path / "vera"))
        model2 = self._model()
        vera2 = VeRAModel.from_pretrained(model2, str(tmp_path / "vera"))
        np.testing.assert_allclose(np.asarray(ref), np.asarray(vera2(input_ids=ids).logits), atol=1e-5)
