"""Test harness: 8 virtual CPU devices so every multi-chip sharding path runs
without TPU hardware (SURVEY.md §4: the reference's `TestMultipleGpus` local-subprocess
simulator maps to XLA's forced host platform device count)."""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
# a wedged TPU tunnel can BLOCK jax backend init even under JAX_PLATFORMS=cpu
# (the axon PJRT plugin registers at discovery time): drop its site dir from
# the import path before jax ever loads
sys.path[:] = [p for p in sys.path if "axon" not in p]
if os.environ.get("PYTHONPATH"):
    os.environ["PYTHONPATH"] = os.pathsep.join(
        p for p in os.environ["PYTHONPATH"].split(os.pathsep) if "axon" not in p)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
