"""Test harness: 8 virtual CPU devices so every multi-chip sharding path runs
without TPU hardware (SURVEY.md §4: the reference's `TestMultipleGpus` local-subprocess
simulator maps to XLA's forced host platform device count)."""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
