"""tier-1 enforcement + unit coverage of the static-analysis suite
(`python -m tools.analyze`): a seeded fixture violation of every rule class
is detected, known-clean fixtures pass, the baseline ratchet freezes old
findings / fails new ones / warns on stale entries, and a smoke run over the
real tree is clean (zero unbaselined findings) and fast (<10s, no jax)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.analyze import AnalysisContext, CHECKERS, Finding, run_checkers  # noqa: E402
from tools.analyze.baseline import (apply_baseline, load_baseline,  # noqa: E402
                                    write_baseline)
import tools.analyze.checkers  # noqa: E402,F401 — register all checkers


def _ctx(tmp_path, files, **config):
    for rel, body in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(body))
    return AnalysisContext(str(tmp_path), config=config)


def _run(name, ctx):
    return CHECKERS[name].run(ctx)


# ----------------------------------------------------------------- jit purity
class TestJitPurity:
    def test_detects_impurity_through_call_chain(self, tmp_path):
        ctx = _ctx(tmp_path, {"pkg/mod.py": """
            import jax, time

            def helper(x):
                print(x)
                return x

            def noisy_clock(x):
                return x * time.time()

            def step(x):
                return helper(noisy_clock(x))

            _step = jax.jit(step, donate_argnums=(0,))
            """}, scan_dirs=["pkg"], jit_graph_dirs=["pkg"])
        findings = _run("jit-purity", ctx)
        msgs = [f.message for f in findings]
        assert any("print" in m for m in msgs), msgs
        assert any("time.time" in m for m in msgs), msgs

    def test_method_seed_and_self_mutation(self, tmp_path):
        ctx = _ctx(tmp_path, {"pkg/mod.py": """
            import jax

            class Model:
                def _build_jits(self):
                    self._f = jax.jit(self._impl, donate_argnums=(1,))

                def _impl(self, x):
                    self.cache = x
                    return x
            """}, scan_dirs=["pkg"], jit_graph_dirs=["pkg"])
        findings = _run("jit-purity", ctx)
        assert any("mutates instance state self.cache" in f.message for f in findings)
        assert findings[0].scope == "Model._impl"

    def test_pallas_kernel_seed_via_partial_alias(self, tmp_path):
        ctx = _ctx(tmp_path, {"pkg/mod.py": """
            import functools
            import numpy as np
            from jax.experimental import pallas as pl

            def _kernel(ref, out):
                out[...] = ref[...] * np.random.rand()

            def call(x):
                k = functools.partial(_kernel)
                return pl.pallas_call(k, out_shape=x)(x)
            """}, scan_dirs=["pkg"], jit_graph_dirs=["pkg"])
        findings = _run("jit-purity", ctx)
        assert any("np.random" in f.message for f in findings)

    def test_clean_and_jit_ok_suppression(self, tmp_path):
        ctx = _ctx(tmp_path, {"pkg/mod.py": """
            import jax
            import jax.numpy as jnp

            def step(x):
                print("tracing step")  # jit-ok: one-time trace-marker log
                return jnp.tanh(x)

            _step = jax.jit(step, donate_argnums=(0,))
            """}, scan_dirs=["pkg"], jit_graph_dirs=["pkg"])
        assert _run("jit-purity", ctx) == []


# ------------------------------------------------------------------ host sync
class TestHostSync:
    FILES = {"pkg/hot.py": """
        import numpy as np

        class Engine:
            def step(self, toks):
                x = np.asarray(toks)
                y = toks.item()
                z = int(toks[0])
                ok = np.asarray([1, 2])  # sync-ok: host literal list
                n = int(sum(v for v in [1, 2]))
                return x, y, z, ok, n

            def cold(self, toks):
                return np.asarray(toks)
        """}

    def _config(self):
        return dict(scan_dirs=["pkg"],
                    host_sync_paths={"pkg/hot.py": ["Engine.step"]})

    def test_detects_each_sync_kind_only_in_hot_functions(self, tmp_path):
        ctx = _ctx(tmp_path, self.FILES, **self._config())
        findings = _run("host-sync", ctx)
        kinds = sorted(f.message.split(" in hot path")[0] for f in findings)
        assert len(findings) == 3, findings
        assert any("np.asarray" in k for k in kinds)
        assert any(".item()" in k for k in kinds)
        assert any("int() on an array element" in k for k in kinds)
        # `cold` is not configured hot; the sync-ok line and the int(sum(...))
        # host math are both exempt
        assert all(f.scope == "Engine.step" for f in findings)

    def test_trailing_annotation_does_not_bleed_to_next_line(self, tmp_path):
        """A `# sync-ok:` trailing one construct must not allowlist a new
        undocumented sync on the line directly below it; a comment-ONLY line
        above still does."""
        ctx = _ctx(tmp_path, {"pkg/hot.py": """
            import numpy as np

            class Engine:
                def step(self, toks):
                    a = np.asarray([1])  # sync-ok: host literal
                    b = toks.item()
                    # sync-ok: standalone annotation covers the next line
                    c = np.asarray(toks)
                    return a, b, c
            """}, scan_dirs=["pkg"],
            host_sync_paths={"pkg/hot.py": ["Engine.step"]})
        findings = _run("host-sync", ctx)
        assert len(findings) == 1 and ".item()" in findings[0].message, findings

    def test_missing_configured_function_is_a_finding(self, tmp_path):
        ctx = _ctx(tmp_path, self.FILES, scan_dirs=["pkg"],
                   host_sync_paths={"pkg/hot.py": ["Engine.renamed_away"]})
        findings = _run("host-sync", ctx)
        assert any("not found" in f.message for f in findings)


# ---------------------------------------------------------- sharding contract
class TestShardingContract:
    def test_sharded_jit_missing_shardings(self, tmp_path):
        ctx = _ctx(tmp_path, {
            "pkg/base.py": """
                import jax

                class Base:
                    def _build_jits(self):
                        self._a = jax.jit(self._a_impl, donate_argnums=(1,))
                        self._b = jax.jit(self._b_impl, donate_argnums=(1,))
                """,
            "pkg/sharded.py": """
                import jax

                class Sharded(Base):
                    def _build_jits(self):
                        self._a = jax.jit(self._a_impl, donate_argnums=(1,),
                                          in_shardings=None, out_shardings=None)
                        self._b = jax.jit(self._b_impl)
                """,
        }, scan_dirs=["pkg"], sharding_base_file="pkg/base.py",
           sharding_sharded_file="pkg/sharded.py", sharding_extra_dirs=["pkg"])
        findings = _run("sharding-contract", ctx)
        assert any("_b_impl) missing explicit in_shardings, out_shardings, "
                   "donate_argnums" in f.message for f in findings), findings

    def test_base_sharded_jit_set_drift(self, tmp_path):
        ctx = _ctx(tmp_path, {
            "pkg/base.py": """
                import jax

                class Base:
                    def _build_jits(self):
                        self._a = jax.jit(self._a_impl, donate_argnums=(1,))
                        self._new = jax.jit(self._new_impl, donate_argnums=(1,))
                """,
            "pkg/sharded.py": """
                import jax

                class Sharded(Base):
                    def _build_jits(self):
                        self._a = jax.jit(self._a_impl, donate_argnums=(1,),
                                          in_shardings=None, out_shardings=None)
                """,
        }, scan_dirs=["pkg"], sharding_base_file="pkg/base.py",
           sharding_sharded_file="pkg/sharded.py", sharding_extra_dirs=["pkg"])
        findings = _run("sharding-contract", ctx)
        assert any("base _build_jits compiles _new_impl" in f.message
                   for f in findings), findings

    def test_engine_tree_jit_without_donation(self, tmp_path):
        ctx = _ctx(tmp_path, {
            "pkg/base.py": "import jax\n_f = jax.jit(lambda x: x)\n",
            "pkg/sharded.py": "class Sharded:\n    pass\n",
        }, scan_dirs=["pkg"], sharding_base_file="pkg/base.py",
           sharding_sharded_file="pkg/sharded.py", sharding_extra_dirs=["pkg"])
        findings = _run("sharding-contract", ctx)
        assert any("without donate_argnums" in f.message for f in findings)


# ------------------------------------------------------------ lock discipline
class TestLockDiscipline:
    FILES = {"pkg/locks.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []  # guarded-by: _lock

            def bad(self):
                return len(self.items)

            def good(self):
                with self._lock:
                    return len(self.items)

            def tolerated(self):
                return self.items  # lock-ok: snapshot read, staleness is fine

            def helper(self):  # holds-lock: _lock
                self.items.append(1)
        """}

    def test_unguarded_access_detected_guarded_paths_clean(self, tmp_path):
        ctx = _ctx(tmp_path, self.FILES, scan_dirs=["pkg"])
        findings = _run("lock-discipline", ctx)
        assert len(findings) == 1, findings
        assert findings[0].scope == "Box.bad"
        assert "guarded-by _lock" in findings[0].message

    def test_unknown_lock_and_malformed_annotation(self, tmp_path):
        ctx = _ctx(tmp_path, {"pkg/locks.py": """
            class Box:
                def __init__(self):
                    # guarded-by: _floating
                    self.items = []  # guarded-by: _nope
            """}, scan_dirs=["pkg"])
        msgs = [f.message for f in _run("lock-discipline", ctx)]
        assert any("never creates self._nope" in m for m in msgs)
        assert any("malformed" in m for m in msgs)


# ------------------------------------------------------------------- catalogs
class TestCatalogs:
    def test_faults_catalog_fixture(self, tmp_path):
        ctx = _ctx(tmp_path, {
            "cat/faults.py": """
                CATALOG = {
                    "a.used": "a documented fault point for tests",
                    "b.dead": "registered but wired to nothing at all",
                    "c.undoc": "TODO",
                }
                """,
            "src/mod.py": """
                P = FaultPoint("a.used")
                Q = FaultPoint("d.unregistered")
                FAULTS.arm("c.undoc")
                """,
        }, scan_dirs=["src"], faults_module="cat/faults.py", catalog_src_dir="src")
        msgs = [f.message for f in _run("faults-catalog", ctx)]
        assert any("'d.unregistered' used" in m for m in msgs)
        assert any("'b.dead' has no call site" in m for m in msgs)
        assert any("'c.undoc' has no meaningful doc" in m for m in msgs)
        assert not any("a.used" in m for m in msgs)

    def test_span_catalog_fixture(self, tmp_path):
        ctx = _ctx(tmp_path, {
            "cat/spans.py": """
                SPAN_CATALOG = {
                    "good": "a documented span name used by the fixture",
                    "stale": "documented but emitted from nowhere any more",
                    "dyn_a": "declared via a span-names annotation below",
                }
                """,
            "src/mod.py": """
                TRACER.span("good", cat="x")
                TRACER.instant("undocumented", cat="x")
                TRACER.add_span(name, 0, 1)  # span-names: dyn_a

                tracer.add_span(other, 0, 1)
                """,
        }, scan_dirs=["src"], span_catalog_module="cat/spans.py",
           catalog_src_dir="src")
        findings = _run("span-catalog", ctx)
        msgs = [f.message for f in findings]
        assert any("'undocumented'" in m and "not in" in m for m in msgs)
        assert any("'stale' has no call site" in m for m in msgs)
        assert any("dynamic span name" in m for m in msgs)
        assert not any("'good'" in m for m in msgs)
        assert not any("dyn_a" in m for m in msgs)
        # fingerprint contract: undocumented-name messages carry files, never
        # call-site line numbers (those ride Finding.line for display only)
        undoc = next(f for f in findings if "'undocumented'" in f.message)
        assert ":2" not in undoc.message and undoc.line > 0

    def test_span_names_annotation_does_not_bleed_down(self, tmp_path):
        ctx = _ctx(tmp_path, {
            "cat/spans.py": 'SPAN_CATALOG = {"dyn_a": "declared dynamic span name set"}\n',
            "src/mod.py": """
                TRACER.add_span(name, 0, 1)  # span-names: dyn_a
                TRACER.add_span(other, 0, 1)
                """,
        }, scan_dirs=["src"], span_catalog_module="cat/spans.py",
           catalog_src_dir="src")
        msgs = [f.message for f in _run("span-catalog", ctx)]
        assert any("dynamic span name" in m for m in msgs), msgs

    def test_event_catalog_fixture(self, tmp_path):
        ctx = _ctx(tmp_path, {
            "cat/events.py": """
                EVENT_CATALOG = {
                    "good.used": "a documented decision event used by the fixture",
                    "stale.dead": "documented but recorded from nowhere any more",
                    "dyn.a": "declared via an event-names annotation below",
                    "short.doc": "TODO",
                }
                EVENT_REASONS = {
                    "good.used": ("ok",),
                    "ghost.event": ("oops",),
                }
                """,
            "src/mod.py": """
                RECORDER.record("good.used", reason="ok")
                RECORDER.record("undocumented.event", req_id=1)
                recorder.record(name)  # event-names: dyn.a
                self.recorder.record(other)
                RECORDER.record("short.doc")
                unrelated.record("not_a_decision_event")
                """,
        }, scan_dirs=["src"], event_catalog_module="cat/events.py",
           catalog_src_dir="src")
        findings = _run("event-catalog", ctx)
        msgs = [f.message for f in findings]
        assert any("'undocumented.event'" in m and "not in" in m for m in msgs)
        assert any("'stale.dead' has no call site" in m for m in msgs)
        assert any("'short.doc' has no meaningful doc" in m for m in msgs)
        assert any("dynamic decision-event name" in m for m in msgs)
        assert any("'ghost.event'" in m and "EVENT_CATALOG" in m for m in msgs)
        assert not any("'good.used'" in m for m in msgs)
        assert not any("dyn.a" in m for m in msgs)
        # the narrow receiver set keeps unrelated .record() methods out
        assert not any("not_a_decision_event" in m for m in msgs)
        # fingerprint contract: undocumented-name messages stay line-free
        undoc = next(f for f in findings if "'undocumented.event'" in f.message)
        assert ":3" not in undoc.message and undoc.line > 0

    def test_event_catalog_real_tree_is_clean(self):
        """Both directions hold on the actual repo: every RECORDER.record name
        is cataloged and every catalog entry has a live call site."""
        ctx = AnalysisContext(REPO)
        assert _run("event-catalog", ctx) == []

    def test_metrics_catalog_fixture(self, tmp_path):
        ctx = _ctx(tmp_path, {
            "DOCS.md": "| `app_documented_total` | counter | fine |\n",
            "src/mod.py": """
                def build(r):
                    r.counter("app_documented_total", "ok")
                    r.counter("app_missing_suffix", "counter without _total")
                    r.gauge("app_undocumented_gauge", "no README row")
                """,
        }, scan_dirs=["src"], catalog_src_dir="src", readme_paths=["DOCS.md"])
        msgs = [f.message for f in _run("metrics-catalog", ctx)]
        assert any("does not end in _total" in m for m in msgs)
        assert any("'app_undocumented_gauge' not documented" in m for m in msgs)
        assert not any("app_documented_total" in m for m in msgs)


# ------------------------------------------------------------------- baseline
class TestBaselineRatchet:
    def _findings(self, n=2):
        return [Finding("rule-x", "a.py", 10 + i, "scope", f"violation {i}")
                for i in range(n)]

    def test_baselined_findings_pass_new_fail(self, tmp_path):
        path = str(tmp_path / "BASELINE.json")
        old = self._findings(2)
        write_baseline(old, path)
        baseline = load_baseline(path)
        # same findings -> all baselined, nothing new
        new, baselined, stale = apply_baseline(old, baseline)
        assert (len(new), baselined, stale) == (0, 2, [])
        # one extra finding -> exactly it is new (ratchet holds the old two)
        extra = Finding("rule-x", "a.py", 99, "scope", "violation NEW")
        new, baselined, stale = apply_baseline(old + [extra], baseline)
        assert [f.message for f in new] == ["violation NEW"]
        assert baselined == 2

    def test_stale_entries_warn_not_fail(self, tmp_path):
        path = str(tmp_path / "BASELINE.json")
        write_baseline(self._findings(2), path)
        baseline = load_baseline(path)
        new, baselined, stale = apply_baseline(self._findings(1), baseline)
        assert new == [] and baselined == 1
        assert len(stale) == 1 and stale[0]["missing"] == 1

    def test_duplicate_fingerprints_ratchet_by_count(self, tmp_path):
        path = str(tmp_path / "BASELINE.json")
        dup = [Finding("r", "a.py", 1, "s", "same construct"),
               Finding("r", "a.py", 2, "s", "same construct")]
        write_baseline(dup, path)
        baseline = load_baseline(path)
        assert list(baseline["entries"].values())[0]["count"] == 2
        new, baselined, _ = apply_baseline(dup + [
            Finding("r", "a.py", 3, "s", "same construct")], baseline)
        assert len(new) == 1 and baselined == 2

    def test_write_preserves_justifications(self, tmp_path):
        path = str(tmp_path / "BASELINE.json")
        f = self._findings(1)
        data = write_baseline(f, path)
        fp = next(iter(data["entries"]))
        data["entries"][fp]["justification"] = "known host-side list"
        with open(path, "w") as fh:
            json.dump(data, fh)
        data2 = write_baseline(f, path)
        assert data2["entries"][fp]["justification"] == "known host-side list"

    def test_fingerprint_survives_line_shift(self):
        a = Finding("r", "a.py", 10, "s", "msg")
        b = Finding("r", "a.py", 200, "s", "msg")
        assert a.fingerprint == b.fingerprint

    def test_filtered_write_preserves_other_rules(self, tmp_path):
        """--write-baseline on a --checker-filtered run must not wipe entries
        (and justifications) belonging to checkers that did not run."""
        path = str(tmp_path / "BASELINE.json")
        other = Finding("host-sync", "b.py", 5, "s", "documented sync")
        data = write_baseline([other], path)
        fp = next(iter(data["entries"]))
        data["entries"][fp]["justification"] = "keep me"
        with open(path, "w") as fh:
            json.dump(data, fh)
        mine = Finding("jit-purity", "a.py", 1, "s", "new impurity")
        data2 = write_baseline([mine], path,
                               keep_entry=lambda e: e.get("rule") != "jit-purity")
        assert fp in data2["entries"]
        assert data2["entries"][fp]["justification"] == "keep me"
        assert len(data2["entries"]) == 2


# ------------------------------------------------------------------ real tree
class TestRealTree:
    def test_smoke_run_clean_fast_and_jaxfree(self):
        """tier-1 wiring: the whole suite over the real repo must be clean
        (zero unbaselined findings), run all checkers, finish well inside the
        10s budget, and never import jax (it is not installed into the lint's
        import path on CI boxes that run it standalone)."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze"], capture_output=True,
            text=True, timeout=60, cwd=REPO,
        )
        line = next((ln for ln in reversed(proc.stdout.strip().splitlines())
                     if ln.startswith("{")), None)
        assert line is not None, f"no JSON output (rc={proc.returncode}): {proc.stderr[-2000:]}"
        report = json.loads(line)
        assert proc.returncode == 0 and report["ok"], report["new_findings"]
        assert report["checkers"] >= 5
        for rule in ("jit-purity", "host-sync", "sharding-contract",
                     "lock-discipline", "faults-catalog", "span-catalog",
                     "metrics-catalog"):
            assert rule in report["per_checker"], report["per_checker"]
        assert report["duration_s"] < 10
        assert report["stale"] == 0, report["stale_entries"]

    def test_no_jax_import_at_lint_time(self):
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.modules['jax'] = None\n"  # poison: import jax -> TypeError
             "from tools.analyze import AnalysisContext, run_checkers\n"
             "f, per = run_checkers(AnalysisContext('.'))\n"
             "print(len(per))"],
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert int(proc.stdout.strip().splitlines()[-1]) >= 5

    def test_real_annotations_are_live(self):
        """The conventions the checkers consume exist in the tree: guarded-by
        annotations on all four serving/observability classes and the span
        catalog covering every literal span name."""
        ctx = AnalysisContext(REPO)
        src = ctx.source("paddlenlp_tpu/serving/scheduler.py")
        assert "# guarded-by: _lock" in src
        for rel in ("paddlenlp_tpu/serving/router/pool.py",
                    "paddlenlp_tpu/observability/tracer.py",
                    "paddlenlp_tpu/serving/engine_loop.py"):
            assert "guarded-by:" in ctx.source(rel), rel

    def test_seeded_violation_detected_in_repo_layout(self, tmp_path):
        """End-to-end ratchet: drop a new host-sync violation into a copy of a
        hot-path file's config and confirm the runner exits 1 with it as NEW."""
        ctx = _ctx(tmp_path, {"pkg/hot.py": """
            import numpy as np

            class Engine:
                def step(self, t):
                    return t.item()
            """}, scan_dirs=["pkg"], host_sync_paths={"pkg/hot.py": ["Engine.step"]})
        findings = _run("host-sync", ctx)
        new, baselined, _ = apply_baseline(findings, {"version": 1, "entries": {}})
        assert len(new) == 1 and baselined == 0


class TestResolveRelative:
    def test_too_deep_relative_import_is_unresolvable(self):
        from tools.analyze.checkers.jit_purity import _resolve_relative
        assert _resolve_relative("pkg/sub/mod.py", 2, "x") == "pkg/x.py"
        assert _resolve_relative("pkg/sub/mod.py", 3, "x") == "x.py"
        assert _resolve_relative("pkg/sub/mod.py", 4, "x") is None


class TestCheckerRegistry:
    def test_all_expected_checkers_registered(self):
        for rule in ("jit-purity", "host-sync", "sharding-contract",
                     "lock-discipline", "faults-catalog", "span-catalog",
                     "metrics-catalog"):
            assert rule in CHECKERS

    def test_unknown_checker_raises(self):
        with pytest.raises(KeyError):
            run_checkers(AnalysisContext(REPO), ["no-such-checker"])

    def test_parse_error_becomes_finding(self, tmp_path):
        ctx = _ctx(tmp_path, {"pkg/broken.py": "def oops(:\n"},
                   scan_dirs=["pkg"])
        ctx.tree("pkg/broken.py")
        assert ctx.parse_errors and ctx.parse_errors[0].rule == "parse-error"
