"""tools/bench_compare.py: the bench perf-regression gate (ISSUE 15).

Drives the pure ``compare()`` core on synthetic bench records (a tier-1 run
cannot afford two real bench runs) and the CLI contract (rc 0 pass / rc 1
regression / rc 2 usage) through a subprocess. The committed
``tools/BENCH_BASELINE.json`` must itself be a loadable, self-consistent
record — the gate's default baseline cannot be allowed to rot."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from tools.bench_compare import DEFAULT_BASELINE, compare, load_record  # noqa: E402

TOOL = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "..", "tools", "bench_compare.py")


def record(value=4.0, tps=50.0, ttft=2000.0, inter=30.0, ratio=0.45,
           wasted=None, compiles=30, gap=1.0):
    return {
        "metric": "serve_smoke_requests_per_sec",
        "value": value,
        "tokens_per_sec": tps,
        "p99_ttft_ms": ttft,
        "p99_inter_token_ms": inter,
        "goodput": {
            "ratio": ratio,
            "fed_tokens": 400,
            "useful_tokens": int(400 * ratio),
            "wasted_tokens": wasted if wasted is not None
            else {"padding": 400 - int(400 * ratio)},
            "compiles": compiles,
            "step_gap_p99_ms": gap,
        },
    }


class TestCompareCore:
    def test_identical_records_pass(self):
        regs, skipped, compared = compare(record(), record())
        assert regs == [] and skipped == [] and compared == 8

    def test_throughput_collapse_fails(self):
        regs, _s, _c = compare(record(value=1.0, tps=10.0), record())
        fields = {r["field"] for r in regs}
        assert {"value", "tokens_per_sec"} <= fields

    def test_goodput_ratio_drop_fails_even_with_good_latency(self):
        # the deterministic gate: padding doubled, wall-clock unchanged
        regs, _s, _c = compare(record(ratio=0.20), record(ratio=0.45))
        assert [r["field"] for r in regs] == ["goodput.ratio", "goodput.waste_share"]

    def test_compile_storm_fails(self):
        regs, _s, _c = compare(record(compiles=200), record(compiles=30))
        assert [r["field"] for r in regs] == ["goodput.compiles"]

    def test_latency_band_has_absolute_slack(self):
        # a 1ms -> 40ms step-gap move is scheduler noise, not a regression
        regs, _s, _c = compare(record(gap=40.0), record(gap=1.0))
        assert regs == []
        regs, _s, _c = compare(record(gap=80.0), record(gap=1.0))
        assert [r["field"] for r in regs] == ["goodput.step_gap_p99_ms"]

    def test_missing_fields_skip_not_fail(self):
        cand = record()
        del cand["goodput"]
        regs, skipped, compared = compare(cand, record())
        assert regs == []
        assert "goodput.ratio" in skipped and "goodput.compiles" in skipped
        assert compared == 4

    def test_tolerances_are_tunable(self):
        regs, _s, _c = compare(record(value=2.5), record(value=4.0),
                               min_throughput_ratio=0.9)
        assert [r["field"] for r in regs] == ["value"]


class TestCommittedBaseline:
    def test_baseline_loads_and_self_compares_clean(self):
        base = load_record(DEFAULT_BASELINE)
        assert base.get("error") is None
        assert base["goodput"]["fed_tokens"] >= base["goodput"]["useful_tokens"]
        regs, _s, compared = compare(base, base)
        assert regs == [] and compared == 8


class TestCli:
    def run_cli(self, *args):
        return subprocess.run([sys.executable, TOOL, *args],
                              capture_output=True, text=True, timeout=60)

    def test_pass_and_regress_and_usage(self, tmp_path):
        base = tmp_path / "base.json"
        base.write_text(json.dumps(record()) + "\n")
        good = tmp_path / "good.json"
        good.write_text("some log line\n" + json.dumps(record(value=3.9)) + "\n")
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(record(value=0.5, ratio=0.1)) + "\n")

        ok = self.run_cli(str(good), str(base))
        assert ok.returncode == 0, ok.stdout + ok.stderr
        doc = json.loads(ok.stdout)
        assert doc["ok"] is True and doc["compared"] == 8

        regressed = self.run_cli(str(bad), str(base))
        assert regressed.returncode == 1
        doc = json.loads(regressed.stdout)
        assert doc["ok"] is False
        assert {r["field"] for r in doc["regressions"]} >= {"value", "goodput.ratio"}

        usage = self.run_cli()
        assert usage.returncode == 2

        # a typo'd tolerance flag must be rc 2, not a gate silently running
        # with defaults (and --flag=value must work like every other tool)
        typo = self.run_cli(str(good), str(base), "--max-goodput-dro", "0.05")
        assert typo.returncode == 2
        assert "unrecognized" in json.loads(typo.stdout)["error"]
        eq_form = self.run_cli(str(good), str(base), "--max-goodput-drop=0.05")
        assert eq_form.returncode == 0

        # zero comparable fields = the gate never ran -> rc 2, never a pass
        alien = tmp_path / "alien.json"
        alien.write_text(json.dumps({"event": "shutdown"}) + "\n")
        never_ran = self.run_cli(str(alien), str(base))
        assert never_ran.returncode == 2
        assert "no comparable fields" in json.loads(never_ran.stdout)["error"]

        errored = tmp_path / "err.json"
        errored.write_text(json.dumps({"error": "boom", "value": 0.0}) + "\n")
        rc = self.run_cli(str(errored), str(base))
        assert rc.returncode == 2  # failed bench record is a usage error, not a pass
