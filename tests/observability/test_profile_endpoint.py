"""On-demand device profiling tests (ISSUE 6): the ``POST /debug/profile``
capture guard — one capture at a time (concurrent -> 409), window validation,
and the HTTP plumbing on the exporter. The jax profiler is replaced by a fake
so the suite stays engine-free and fast."""

import http.client
import json
import os
import threading
import time

import pytest

from paddlenlp_tpu.observability import ObservabilityExporter, ProfileCapture
from paddlenlp_tpu.observability.exporter import (
    ProfileInProgressError,
    handle_profile_request,
)
from paddlenlp_tpu.serving.metrics import MetricsRegistry


class FakeProfiler:
    """Records start/stop calls; optionally blocks inside the window."""

    def __init__(self):
        self.traces = []  # paths passed to start_trace
        self.active = False
        self.started = threading.Event()

    def start_trace(self, path):
        assert not self.active, "overlapping start_trace: the guard failed"
        self.active = True
        self.traces.append(path)
        self.started.set()

    def stop_trace(self):
        self.active = False


@pytest.fixture
def capture(tmp_path):
    return ProfileCapture(base_dir=str(tmp_path), max_seconds=2.0,
                          profiler=FakeProfiler())


class TestProfileCapture:
    def test_capture_returns_path(self, capture):
        out = capture.capture(0.01)
        assert out["seconds"] == 0.01
        assert os.path.isdir(out["path"])
        assert capture._profiler.traces == [out["path"]]
        assert not capture._profiler.active  # stopped even on success

    def test_sequential_captures_get_distinct_paths(self, capture):
        a = capture.capture(0.01)["path"]
        b = capture.capture(0.01)["path"]
        assert a != b

    def test_concurrent_capture_rejected(self, capture):
        fake = capture._profiler
        done = threading.Event()

        def long_capture():
            capture.capture(0.5)
            done.set()

        t = threading.Thread(target=long_capture, daemon=True)
        t.start()
        assert fake.started.wait(2.0)
        with pytest.raises(ProfileInProgressError):
            capture.capture(0.01)
        assert done.wait(5.0)
        # guard released: the next capture goes through
        assert capture.capture(0.01)["seconds"] == 0.01

    def test_window_validation(self, capture):
        with pytest.raises(ValueError):
            capture.capture(0.0)
        with pytest.raises(ValueError):
            capture.capture(-1.0)
        with pytest.raises(ValueError):
            capture.capture(100.0)  # > max_seconds
        assert capture._profiler.traces == []  # rejected before start_trace

    def test_stop_trace_on_failure_releases_guard(self, tmp_path):
        class Boom(FakeProfiler):
            def start_trace(self, path):
                raise RuntimeError("no backend")

        cap = ProfileCapture(base_dir=str(tmp_path), profiler=Boom())
        with pytest.raises(RuntimeError):
            cap.capture(0.01)
        # lock released: a retry raises the backend error again, not 409
        with pytest.raises(RuntimeError):
            cap.capture(0.01)


class TestHandleProfileRequest:
    def test_path_mismatch_returns_none(self, capture):
        assert handle_profile_request("/v1/completions", capture) is None
        assert handle_profile_request("/debug/trace", capture) is None

    def test_ok_request(self, capture):
        status, ctype, body = handle_profile_request("/debug/profile?seconds=0.01",
                                                     capture)
        assert status == 200 and ctype == "application/json"
        assert os.path.isdir(json.loads(body)["path"])

    def test_bad_seconds(self, capture):
        status, _, body = handle_profile_request("/debug/profile?seconds=nope", capture)
        assert status == 400
        status, _, body = handle_profile_request("/debug/profile?seconds=-3", capture)
        assert status == 400 and json.loads(body)["type"] == "invalid_request"

    def test_concurrent_is_409(self, capture):
        fake = capture._profiler
        t = threading.Thread(target=lambda: capture.capture(0.5), daemon=True)
        t.start()
        assert fake.started.wait(2.0)
        status, _, body = handle_profile_request("/debug/profile?seconds=0.01", capture)
        assert status == 409
        assert json.loads(body)["type"] == "profile_in_progress"
        t.join(5.0)

    def test_backend_failure_is_500(self, tmp_path):
        class Boom(FakeProfiler):
            def start_trace(self, path):
                raise RuntimeError("no backend")

        cap = ProfileCapture(base_dir=str(tmp_path), profiler=Boom())
        status, _, body = handle_profile_request("/debug/profile?seconds=0.01", cap)
        assert status == 500 and json.loads(body)["type"] == "profile_failed"


class TestExporterEndpoint:
    def test_post_profile_over_http(self, tmp_path):
        cap = ProfileCapture(base_dir=str(tmp_path), profiler=FakeProfiler())
        exporter = ObservabilityExporter(registry=MetricsRegistry(), profile=cap)
        port = exporter.start(port=0)
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            conn.request("POST", "/debug/profile?seconds=0.01")
            resp = conn.getresponse()
            body = json.loads(resp.read())
            conn.close()
            assert resp.status == 200 and os.path.isdir(body["path"])
            # unknown POST routes still 404
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            conn.request("POST", "/nope")
            resp = conn.getresponse()
            resp.read()
            conn.close()
            assert resp.status == 404
        finally:
            exporter.shutdown()

    def test_post_with_body_keeps_keepalive_in_sync(self, tmp_path):
        # both HTTP planes are HTTP/1.1 keep-alive: an unread request body
        # (curl -d '{}') left on the socket would be parsed as the NEXT
        # request's start line — the handler must drain it before responding
        cap = ProfileCapture(base_dir=str(tmp_path), profiler=FakeProfiler())
        exporter = ObservabilityExporter(registry=MetricsRegistry(), profile=cap)
        port = exporter.start(port=0)
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            conn.request("POST", "/debug/profile?seconds=0.01", body=b'{"why": "not"}',
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            resp.read()
            # second request on the SAME connection must not see body leftovers
            conn.request("POST", "/debug/profile?seconds=0.01")
            resp = conn.getresponse()
            assert resp.status == 200
            resp.read()
            conn.close()
        finally:
            exporter.shutdown()
