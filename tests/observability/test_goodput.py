"""GoodputLedger unit contract: exact conservation, compile attribution,
FLOPs/MFU model, and the /debug/efficiency doc shape. Pure stdlib — no jax,
no engine (the engine-level parity lives in
tests/experimental/test_goodput_ledger.py)."""

import json
import math
import threading

import pytest

from paddlenlp_tpu.observability.goodput import (
    GoodputLedger,
    _on_duration,
    compile_attribution,
    device_peak_flops,
    efficiency_doc,
    estimate_model_flops_per_token,
)


class TestConservation:
    def test_record_accumulates(self):
        led = GoodputLedger()
        led.record("prefill", 32, 20, padding=10, rework=2)
        led.record("decode", 16, 4, padding=12)
        led.record("verify", 10, 3, padding=5, spec_rejected=2)
        assert led.totals == {"fed": 58, "useful": 27, "padding": 27,
                              "spec_rejected": 2, "rework": 2}
        assert led.verify_conservation()
        assert led.ratio() == pytest.approx(27 / 58)
        assert led.by_kind["prefill"] == {"steps": 1, "fed": 32, "useful": 20}
        assert led.padding_by["decode"] == 12

    def test_violation_raises(self):
        led = GoodputLedger()
        with pytest.raises(ValueError, match="conservation violated"):
            led.record("prefill", 10, 9, padding=2)  # 9 + 2 != 10
        with pytest.raises(ValueError, match="conservation violated"):
            led.record("decode", 10, 12, padding=-2)  # negative component
        with pytest.raises(ValueError, match="unknown step kind"):
            led.record("nope", 1, 1)
        # a failed record must not have mutated the totals
        assert led.totals["fed"] == 0 and led.verify_conservation()

    def test_rework_attribution_sums_or_raises(self):
        led = GoodputLedger()
        led.record("reseed", 7, 0, rework=7, rework_by={"migration_reseed": 7})
        assert led.rework_by["migration_reseed"] == 7
        with pytest.raises(ValueError, match="does not sum"):
            led.record("prefill", 5, 2, padding=1, rework=2,
                       rework_by={"cow_token": 1})
        # unattributed rework defaults to the preemption bucket
        led.record("prefill", 4, 1, padding=1, rework=2)
        assert led.rework_by["preempt_refill"] == 2
        assert led.verify_conservation()

    def test_idle_ledger_reads_clean(self):
        led = GoodputLedger()
        assert led.ratio() == 1.0
        assert math.isnan(led.mfu())
        assert led.verify_conservation()
        snap = led.snapshot()
        assert snap["totals"]["fed"] == 0
        assert snap["by_kind"] == {} and snap["padding_by"] == {}


class TestCompileTelemetry:
    def test_attribution_is_per_thread(self):
        mine, other = GoodputLedger(), GoodputLedger()
        with compile_attribution(mine, "prefill"):
            _on_duration("jax/backend_compile", 1.5)
            # another thread compiling concurrently attributes to ITS ledger
            def other_thread():
                with compile_attribution(other, "decode"):
                    _on_duration("jax/backend_compile", 0.5)
            t = threading.Thread(target=other_thread)
            t.start()
            t.join()
        _on_duration("jax/backend_compile", 9.0)  # outside any block: dropped
        assert mine.compiles == {"prefill": 1}
        assert mine.compile_seconds == {"prefill": 1.5}
        assert other.compiles == {"decode": 1}

    def test_non_compile_events_ignored_and_nesting_restores(self):
        led = GoodputLedger()
        with compile_attribution(led, "mixed"):
            _on_duration("jax/some_other_event", 1.0)
            with compile_attribution(led, "verify"):
                _on_duration("x/backend_compile", 0.25)
            _on_duration("x/backend_compile", 0.25)
        assert led.compiles == {"verify": 1, "mixed": 1}

    def test_none_ledger_noop(self):
        with compile_attribution(None, "prefill"):
            _on_duration("x/backend_compile", 1.0)  # must not raise

    def test_shape_bucket_cardinality(self):
        led = GoodputLedger()
        led.note_shape(("prefill", 2, 16))
        led.note_shape(("prefill", 2, 16))
        led.note_shape(("decode", 4, 8))
        assert led.snapshot()["shape_buckets"] == 2


class TestFlopsModel:
    def test_estimate_from_config(self):
        class Cfg:
            hidden_size = 64
            num_hidden_layers = 2
            vocab_size = 96
            intermediate_size = 112
            num_attention_heads = 8
            num_key_value_heads = 4
        # embed+head + layers * (attn(q,o full + k,v at GQA ratio) + 3 mlp)
        attn = 64 * 64 * (2 + 2 * 4 / 8)
        expect = 2.0 * (96 * 64 * 2 + 2 * (attn + 3 * 64 * 112))
        assert estimate_model_flops_per_token(Cfg()) == pytest.approx(expect)

    def test_estimate_nan_on_junk(self):
        class Junk:
            hidden_size = "nope"
        assert math.isnan(estimate_model_flops_per_token(Junk()))
        assert math.isnan(estimate_model_flops_per_token(object()))

    def test_peak_flops_table(self):
        assert device_peak_flops("TPU v5e") == pytest.approx(197e12)
        assert device_peak_flops("TPU v4") == pytest.approx(275e12)
        assert math.isnan(device_peak_flops("cpu"))
        assert math.isnan(device_peak_flops("NVIDIA H100"))

    def test_mfu_real_and_nan(self):
        led = GoodputLedger(flops_per_token=2.0, peak_flops=float("nan"))
        led.record("decode", 10, 10)
        assert math.isnan(led.mfu())  # unknown peak -> NaN, never fake
        led2 = GoodputLedger(flops_per_token=100.0, peak_flops=1000.0)
        led2.record("decode", 10, 5, padding=5)
        led2._first_record_t = 0.0
        led2._last_record_t = 1.0
        assert led2.mfu() == pytest.approx(5 * 100.0 / (1.0 * 1000.0))


class TestEfficiencyDoc:
    def test_doc_shape_and_json_safe(self):
        led = GoodputLedger()
        led.record("mixed", 8, 5, padding=3)
        led.note_step(0.001, 0.05, 0.002)
        doc = efficiency_doc(led, [(1, 0.001, 0.05, 0.002), (2, -1.0, 0.04, 0.001)],
                             extra={"kv_fragmentation": 0.25})
        assert doc["tier"] == "serving"
        assert doc["ledger"]["totals"]["fed"] == 8
        assert doc["mfu"] is None  # NaN serialized as null
        assert doc["kv_fragmentation"] == 0.25
        anatomy = doc["step_anatomy"]
        assert anatomy["window_steps"] == 2
        assert anatomy["device_p99_ms"] == pytest.approx(50.0)
        json.dumps(doc)  # strictly serializable

    def test_unmeasured_gaps_excluded_from_percentiles(self):
        # gap < 0 marks first/post-idle steps: they must not drag the p50 down
        times = [(1, -1.0, 0.01, 0.0), (2, 0.5, 0.01, 0.0), (3, 0.5, 0.01, 0.0)]
        doc = efficiency_doc(None, times)
        assert doc["step_anatomy"]["gap_p50_ms"] == pytest.approx(500.0)
        # an ALL-unmeasured window reports null, never a fake perfect 0.0
        doc = efficiency_doc(None, [(1, -1.0, 0.01, 0.0)])
        assert doc["step_anatomy"]["gap_p50_ms"] is None
        assert doc["step_anatomy"]["gap_p99_ms"] is None
