"""Cross-tier tracing unit tests (ISSUE 6): head-based 1-in-N sampling
determinism and the no-op span path, traceparent header round-trips, and
multi-process Chrome-trace stitching with clock-skew correction. Stdlib-only
module — no jax, no HTTP."""

import time

import pytest

from paddlenlp_tpu.observability import (
    SpanTracer,
    format_traceparent,
    merge_chrome_traces,
    parse_traceparent,
    trace_sampled,
)


class TestSamplingDecision:
    def test_deterministic_across_instances_and_processes(self):
        # crc32, not Python hash(): every process that sees the same id
        # independently agrees without coordination
        ids = [f"rtr-{i}" for i in range(512)]
        a = {t for t in ids if trace_sampled(t, 8)}
        b = {t for t in ids if trace_sampled(t, 8)}
        assert a == b
        # roughly 1-in-8 (crc32 is uniform over sequential ids)
        assert 512 / 16 < len(a) < 512 / 4

    def test_sample_every_one_keeps_everything(self):
        assert all(trace_sampled(f"t-{i}", 1) for i in range(32))

    def test_noop_path_span_volume(self):
        # the acceptance-criteria shape: with 1-in-8 sampling, per-request
        # span volume drops >= 4x on a 64-request load while sampled requests
        # keep FULL span detail
        full = SpanTracer(capacity=4096)
        sampled = SpanTracer(capacity=4096, sample_every=8)
        per_request = 3  # queue + prefill + decode retrospective spans
        for tr in (full, sampled):
            for i in range(64):
                rid = f"rtr-{i}"
                for name in ("queue", "prefill", "decode")[:per_request]:
                    tr.add_span(name, time.time(), 0.01, trace=rid, wall=True)
        assert len(full) == 64 * per_request
        assert len(sampled) <= len(full) / 4
        kept = {s.trace for s in sampled.snapshot()}
        assert kept == {f"rtr-{i}" for i in range(64) if trace_sampled(f"rtr-{i}", 8)}
        # sampled traces keep every span, not a thinned subset
        for rid in kept:
            assert len(sampled.snapshot(trace=rid)) == per_request

    def test_traceless_spans_never_sampled_out(self):
        tr = SpanTracer(capacity=64, sample_every=1_000_000)
        with tr.span("engine_phase", cat="engine"):
            pass
        tr.instant("marker")
        assert len(tr) == 2

    def test_mark_overrides_hash(self):
        tr = SpanTracer(capacity=64, sample_every=2)
        ids = [f"t-{i}" for i in range(16)]
        hash_in = next(t for t in ids if trace_sampled(t, 2))
        hash_out = next(t for t in ids if not trace_sampled(t, 2))
        # upstream tier said the opposite of the local hash: the mark wins
        tr.mark_trace(hash_in, False)
        tr.mark_trace(hash_out, True)
        tr.instant("a", trace=hash_in)
        tr.instant("b", trace=hash_out)
        spans = tr.snapshot()
        assert [s.name for s in spans] == ["b"]

    def test_mark_table_is_bounded(self):
        tr = SpanTracer(capacity=64)
        tr._marks_cap = 8
        for i in range(32):
            tr.mark_trace(f"t-{i}", True)
        assert len(tr._trace_marks) == 8
        assert "t-31" in tr._trace_marks and "t-0" not in tr._trace_marks

    def test_context_manager_path_respects_sampling(self):
        tr = SpanTracer(capacity=64, sample_every=1)
        tr.mark_trace("quiet", False)
        with tr.span("w", trace="quiet"):
            pass
        tr.instant("i", trace="quiet")
        tr.add_span("a", time.time(), 0.1, trace="quiet", wall=True)
        assert len(tr) == 0

    def test_validation(self):
        with pytest.raises(ValueError):
            SpanTracer(sample_every=0)


class TestTraceparent:
    def test_round_trip(self):
        v = format_traceparent("rtr-42", "rtr-42@router", False)
        assert parse_traceparent(v) == ("rtr-42", "rtr-42@router", False)
        v = format_traceparent("rtr-7")
        assert parse_traceparent(v) == ("rtr-7", "", True)

    def test_malformed_values(self):
        assert parse_traceparent(None) is None
        assert parse_traceparent("") is None
        assert parse_traceparent(";parent=x") is None
        assert parse_traceparent("has space;sampled=1") is None

    def test_unknown_fields_ignored(self):
        got = parse_traceparent("rtr-1;parent=p;future=thing;sampled=0")
        assert got == ("rtr-1", "p", False)

    def test_sampled_flag_forms(self):
        assert parse_traceparent("t;sampled=0")[2] is False
        assert parse_traceparent("t;sampled=false")[2] is False
        assert parse_traceparent("t;sampled=1")[2] is True
        assert parse_traceparent("t")[2] is True  # default: sampled


class TestMergeChromeTraces:
    def _tier(self, name, spans, offset_s=0.0, dropped=0):
        tr = SpanTracer(capacity=256)
        for sname, start, dur in spans:
            tr.add_span(sname, start, dur, trace="rtr-0")
        return {"name": name, "events": tr.chrome_trace()["traceEvents"],
                "offset_s": offset_s, "dropped": dropped}

    def test_tiers_become_pid_lanes(self):
        t0 = time.time()
        merged = merge_chrome_traces([
            self._tier("router", [("route", t0, 0.001)]),
            self._tier("replica-0", [("prefill", t0, 0.01)]),
        ])
        pids = {ev["pid"] for ev in merged["traceEvents"]}
        assert pids == {1, 2}
        names = {ev["args"]["name"] for ev in merged["traceEvents"]
                 if ev.get("name") == "process_name"}
        assert names == {"router", "replica-0"}

    def test_clock_skew_correction_restores_monotonic_order(self):
        # router span [t0, t0+1.0]; the replica's clock runs 5s AHEAD, so its
        # nested span is recorded at t0+5.2 in replica time. After shifting by
        # -offset the replica span lands back inside the router span.
        t0 = time.time()
        skew = 5.0
        merged = merge_chrome_traces([
            self._tier("router", [("router_request", t0, 1.0)]),
            self._tier("replica-0", [("decode", t0 + skew + 0.2, 0.3)],
                       offset_s=skew),
        ])
        by_name = {ev["name"]: ev for ev in merged["traceEvents"]
                   if ev.get("ph") == "X"}
        router_ev, replica_ev = by_name["router_request"], by_name["decode"]
        assert router_ev["ts"] <= replica_ev["ts"]
        assert (replica_ev["ts"] + replica_ev["dur"]
                <= router_ev["ts"] + router_ev["dur"] + 1)  # us rounding slack
        # corrected, the replica span starts ~0.2s into the router span
        assert replica_ev["ts"] - router_ev["ts"] == pytest.approx(0.2e6, rel=0.05)

    def test_metadata_events_not_shifted(self):
        tier = self._tier("replica-0", [("x", time.time(), 0.1)], offset_s=100.0)
        merged = merge_chrome_traces([tier])
        for ev in merged["traceEvents"]:
            if ev.get("ph") == "M":
                assert "ts" not in ev or ev["ts"] < 1e15  # untouched metadata

    def test_dropped_counts_surface(self):
        merged = merge_chrome_traces([
            self._tier("router", [], dropped=3),
            self._tier("replica-0", [], dropped=7),
        ])
        assert merged["otherData"]["dropped_spans"] == {"router": 3, "replica-0": 7}
