"""Span tracer unit tests: concurrency, ring-buffer eviction, trace-context
propagation, and Chrome trace-event JSON validity (the format /debug/trace
serves and Perfetto loads). No jax, no engine — stdlib-only module."""

import json
import threading
import time

import pytest

from paddlenlp_tpu.observability import SpanTracer, current_trace, use_trace


class TestRecording:
    def test_span_records_duration(self):
        tr = SpanTracer(capacity=16)
        with tr.span("work", cat="test", k=1):
            time.sleep(0.01)
        (s,) = tr.snapshot()
        assert s.name == "work" and s.cat == "test"
        assert s.dur >= 0.01
        assert s.args == {"k": 1}
        assert s.tid == threading.get_ident()

    def test_instant_has_no_duration(self):
        tr = SpanTracer(capacity=16)
        tr.instant("marker", cat="test")
        (s,) = tr.snapshot()
        assert s.dur is None

    def test_mid_span_args_and_error_capture(self):
        tr = SpanTracer(capacity=16)
        with tr.span("w") as sp:
            sp.set(tokens=7)
        with pytest.raises(RuntimeError):
            with tr.span("boom"):
                raise RuntimeError("nope")
        spans = {s.name: s for s in tr.snapshot()}
        assert spans["w"].args == {"tokens": 7}
        assert "RuntimeError" in spans["boom"].args["error"]

    def test_add_span_retrospective(self):
        tr = SpanTracer(capacity=16)
        t0 = time.time() - 1.0
        tr.add_span("late", t0, 0.5, cat="x", trace="req-1", n=2)
        (s,) = tr.snapshot()
        assert s.ts == t0 and s.dur == 0.5 and s.trace == "req-1"

    def test_disabled_tracer_records_nothing(self):
        tr = SpanTracer(capacity=16, enabled=False)
        with tr.span("w"):
            pass
        tr.instant("i")
        tr.add_span("a", time.time(), 0.1)
        assert len(tr) == 0


class TestRingBuffer:
    def test_eviction_keeps_newest(self):
        tr = SpanTracer(capacity=10)
        for i in range(25):
            tr.instant(f"s{i}")
        assert len(tr) == 10
        assert tr.dropped == 15
        assert [s.name for s in tr.snapshot()] == [f"s{i}" for i in range(15, 25)]

    def test_clear(self):
        tr = SpanTracer(capacity=4)
        for i in range(8):
            tr.instant(f"s{i}")
        tr.clear()
        assert len(tr) == 0 and tr.dropped == 0

    def test_concurrent_spans(self):
        tr = SpanTracer(capacity=4096)
        n_threads, per_thread = 8, 100
        # all workers alive until everyone recorded, else the OS recycles
        # thread idents and the distinct-tid assertion undercounts
        barrier = threading.Barrier(n_threads)

        def worker(t):
            for i in range(per_thread):
                with tr.span(f"t{t}-{i}", cat="conc"):
                    pass
            barrier.wait()

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        spans = tr.snapshot()
        assert len(spans) == n_threads * per_thread
        assert len({s.tid for s in spans}) == n_threads
        assert {s.name for s in spans} == {
            f"t{t}-{i}" for t in range(n_threads) for i in range(per_thread)}


class TestTraceContext:
    def test_ambient_trace_propagates(self):
        tr = SpanTracer(capacity=16)
        assert current_trace() is None
        with use_trace("req-7"):
            assert current_trace() == "req-7"
            with tr.span("inner"):
                pass
            tr.instant("mark")
        assert current_trace() is None
        assert all(s.trace == "req-7" for s in tr.snapshot())

    def test_explicit_trace_wins(self):
        tr = SpanTracer(capacity=16)
        with use_trace("ambient"):
            with tr.span("s", trace="explicit"):
                pass
        (s,) = tr.snapshot()
        assert s.trace == "explicit"

    def test_snapshot_filters(self):
        tr = SpanTracer(capacity=16)
        tr.add_span("a", 100.0, 1.0, trace="x")
        tr.add_span("b", 200.0, 1.0, trace="y")
        assert [s.name for s in tr.snapshot(trace="y")] == ["b"]
        assert [s.name for s in tr.snapshot(since_ts=150.0)] == ["b"]


class TestChromeExport:
    def _tracer(self):
        tr = SpanTracer(capacity=64)
        with tr.span("outer", cat="phase", trace="req-0", size=3):
            with tr.span("inner", cat="phase"):
                pass
        tr.instant("evicted", cat="event")
        return tr

    def test_chrome_trace_json_valid(self):
        tr = self._tracer()
        parsed = json.loads(json.dumps(tr.chrome_trace()))
        events = parsed["traceEvents"]
        assert events, "no events exported"
        complete = [e for e in events if e["ph"] == "X"]
        instants = [e for e in events if e["ph"] == "i"]
        meta = [e for e in events if e["ph"] == "M"]
        assert {e["name"] for e in complete} == {"outer", "inner"}
        assert {e["name"] for e in instants} == {"evicted"}
        for e in complete:
            assert {"name", "cat", "ph", "ts", "pid", "tid", "dur"} <= set(e)
            assert isinstance(e["ts"], (int, float)) and e["dur"] >= 0
        for e in instants:
            assert "dur" not in e and e["s"] == "t"
        # thread metadata names the lane
        assert any(e["name"] == "thread_name" and e["args"]["name"] for e in meta)
        # trace id rides on args
        outer = next(e for e in complete if e["name"] == "outer")
        assert outer["args"]["trace"] == "req-0" and outer["args"]["size"] == 3

    def test_inner_nested_within_outer(self):
        tr = self._tracer()
        ev = {e["name"]: e for e in tr.chrome_trace()["traceEvents"] if e["ph"] == "X"}
        o, i = ev["outer"], ev["inner"]
        assert o["ts"] <= i["ts"] and i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1e-3

    def test_jsonl_export(self):
        tr = self._tracer()
        lines = tr.to_jsonl().splitlines()
        assert len(lines) == 3
        for line in lines:
            d = json.loads(line)
            assert {"name", "ts", "tid", "thread"} <= set(d)

    def test_write_chrome_trace(self, tmp_path):
        tr = self._tracer()
        path = str(tmp_path / "trace.json")
        tr.write_chrome_trace(path)
        with open(path) as f:
            assert json.load(f)["traceEvents"]
