"""Usage ledger + meter + offline report unit tests (no engine, no HTTP).

Covers the durability contract end to end: segment rotation and atomic
sealing, torn-tail and sealed/open-twin tolerance on reload, the
``usage.seal`` fault point's partial-write chaos window, the meter's
exactly-once booking (trace-id dedup, handle and no-handle paths), and
``tools/usage_report.py``'s merge/dedup/price/reconcile including the
double-bill conflict exit code."""

import json
import os
import sys

import pytest

from paddlenlp_tpu.observability.usage import (
    SUM_FIELDS,
    UsageLedger,
    empty_aggregate,
    fold_record,
    load_ledger_dir,
    merge_aggregates,
)
from paddlenlp_tpu.serving.tenancy.metering import UsageMeter
from paddlenlp_tpu.utils.faults import FAULTS, InjectedFault

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import usage_report  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def rec(i, tenant="acme", adapter=None, finish="stop", **kw):
    base = {
        "record_id": f"tr-{i}", "tenant": tenant, "adapter_id": adapter,
        "finish_reason": finish, "prompt_tokens": 10, "cached_tokens": 2,
        "completion_tokens": 5, "useful_tokens": 12, "spec_drafted": 0,
        "spec_accepted": 0, "kv_block_seconds": 0.25, "adapter_slot_seconds": 0.0,
    }
    base.update(kw)
    return base


# --------------------------------------------------------------------- ledger
class TestUsageLedger:
    def test_rotation_by_size_and_reload(self, tmp_path):
        led = UsageLedger(str(tmp_path), replica="r0", max_segment_records=3)
        for i in range(7):
            led.append(rec(i))
        # 7 records at 3/segment: two sealed segments, one open with 1 record
        stats = led.stats()
        assert stats["sealed_segments"] == 2
        assert stats["open_records"] == 1
        assert stats["records_total"] == 7
        records, report = load_ledger_dir(str(tmp_path))
        assert report["sealed_segments"] == 2
        assert report["open_segments"] == 1
        assert [r["record_id"] for r in records] == [f"tr-{i}" for i in range(7)]
        led.close()
        # close seals the tail; everything sealed now, nothing lost
        records, report = load_ledger_dir(str(tmp_path))
        assert report["open_segments"] == 0
        assert report["sealed_segments"] == 3
        assert len(records) == 7

    def test_closed_ledger_refuses_appends(self, tmp_path):
        led = UsageLedger(str(tmp_path), replica="r0")
        led.append(rec(0))
        led.close()
        with pytest.raises(RuntimeError):
            led.append(rec(1))

    def test_restart_resumes_past_existing_segments(self, tmp_path):
        led = UsageLedger(str(tmp_path), replica="r0", max_segment_records=1)
        led.append(rec(0))
        led.close()
        # same replica name restarting into the same dir must not overwrite
        led2 = UsageLedger(str(tmp_path), replica="r0", max_segment_records=1)
        led2.append(rec(1))
        led2.close()
        records, report = load_ledger_dir(str(tmp_path))
        assert len(records) == 2
        assert report["sealed_segments"] == 2

    def test_torn_open_tail_dropped_and_counted(self, tmp_path):
        led = UsageLedger(str(tmp_path), replica="r0")
        led.append(rec(0))
        led.append(rec(1))
        # simulate the kill -9 mid-append: torn JSON tail on the open segment
        open_path = led._open_path
        with open(open_path, "a", encoding="utf-8") as f:
            f.write('{"record_id": "tr-torn", "prompt_to')
        records, report = load_ledger_dir(str(tmp_path))
        assert len(records) == 2
        assert report["torn_lines_dropped"] == 1

    def test_sealed_open_twin_prefers_sealed(self, tmp_path):
        led = UsageLedger(str(tmp_path), replica="r0")
        led.append(rec(0))
        open_path = led._open_path
        open_copy = open(open_path, encoding="utf-8").read()
        led.seal()
        # crash between rename-commit and unlink: the open file survives
        with open(open_path, "w", encoding="utf-8") as f:
            f.write(open_copy)
        records, report = load_ledger_dir(str(tmp_path))
        assert len(records) == 1  # not double-counted
        assert report["twins_skipped"] == 1

    def test_seal_fault_partial_leaves_loadable_ledger(self, tmp_path):
        """action="partial" on usage.seal truncates the open segment mid-line
        and raises before the rename — the kill-during-seal chaos case. The
        directory must stay loadable: sealed history intact, the torn tail of
        the open segment dropped + counted."""
        led = UsageLedger(str(tmp_path), replica="r0", max_segment_records=2)
        led.append(rec(0))
        led.append(rec(1))  # seals segment 0
        led.append(rec(2))
        FAULTS.arm("usage.seal", action="partial", nth=1)
        with pytest.raises(InjectedFault):
            led.seal()
        records, report = load_ledger_dir(str(tmp_path))
        assert report["sealed_segments"] == 1
        assert report["open_segments"] == 1
        # segment 0's two records survived; the truncated open tail dropped
        assert [r["record_id"] for r in records] == ["tr-0", "tr-1"]
        assert report["torn_lines_dropped"] == 1


# ----------------------------------------------------------------- aggregates
class TestAggregates:
    def test_fold_and_merge_shapes_agree(self):
        agg = empty_aggregate()
        fold_record(agg, rec(0))
        fold_record(agg, rec(1, tenant="globex", adapter="ad-a"))
        assert agg["records"] == 2
        assert agg["totals"]["prompt_tokens"] == 20
        assert agg["tenants"]["acme"]["records"] == 1
        assert agg["adapters"]["base"]["records"] == 1
        assert agg["adapters"]["ad-a"]["completion_tokens"] == 5
        merged = merge_aggregates([agg, agg])
        assert merged["records"] == 4
        assert merged["totals"]["kv_block_seconds"] == pytest.approx(1.0)
        assert merged["tenants"]["globex"]["useful_tokens"] == 24
        # report-side SUM_FIELDS is a mirror, not an import — keep in lockstep
        assert tuple(usage_report.SUM_FIELDS) == tuple(SUM_FIELDS)


# -------------------------------------------------------------------- meter
class _Req:
    def __init__(self, **kw):
        self.req_id = kw.pop("req_id", 1)
        self.tenant = kw.pop("tenant", "acme")
        self.adapter_id = kw.pop("adapter_id", None)
        self.priority = "interactive"
        self.finish_reason = kw.pop("finish_reason", "stop")
        self.aborted = False
        self.prompt_ids = kw.pop("prompt_ids", [1] * 8)
        self.output_ids = kw.pop("output_ids", [2] * 3)
        self.base_prompt_len = kw.pop("base_prompt_len", len(self.prompt_ids))
        self.cached_tokens = 4
        self.useful_tokens = 6
        self.spec_drafted = 2
        self.spec_accepted = 1
        self.kv_block_seconds = 0.5
        self.adapter_slot_seconds = 0.0
        self.arrival_t = 1.0
        self.finish_t = 2.5
        self.trace = kw.pop("trace", "tr-1")
        for k, v in kw.items():
            setattr(self, k, v)


class _Handle:
    def __init__(self, trace="tr-1", prompt_len=8, streamed=3, retries=1,
                 adapter_id="ad-a"):
        self.trace = trace
        self.prompt_len = prompt_len
        self._streamed = [7] * streamed
        self.retries = retries
        self.adapter_id = adapter_id
        self.tenant = "acme"


class TestUsageMeter:
    def test_trace_id_dedup_books_once(self):
        m = UsageMeter()
        assert m.record_finished(_Req()) is not None
        assert m.record_finished(_Req()) is None  # same trace: suppressed
        snap = m.snapshot()
        assert snap["records"] == 1
        assert snap["duplicates_suppressed"] == 1

    def test_traceless_requests_never_dedup(self):
        m = UsageMeter()
        # engine req_ids restart per engine — two trace-less requests with
        # the same req_id are different requests, both must bill
        assert m.record_finished(_Req(trace=None)) is not None
        assert m.record_finished(_Req(trace=None)) is not None
        assert m.snapshot()["records"] == 2

    def test_handle_path_bills_streamed_tokens(self):
        m = UsageMeter()
        r = m.record_finished(_Req(), _Handle(streamed=5), attribution={"queue": 0.1})
        assert r["prompt_tokens"] == 8
        assert r["completion_tokens"] == 5  # handle truth, not req.output_ids
        assert r["adapter_id"] is None or r["adapter_id"] == "ad-a"
        assert r["retries"] == 1
        assert r["e2e_s"] == pytest.approx(1.5)
        assert r["attribution"] == {"queue": 0.1}

    def test_no_handle_path_bills_folded_tokens_as_completion(self):
        m = UsageMeter()
        # a preemption folded 4 generated tokens into prompt_ids: prompt is
        # the original 8, the folded 4 + 3 output bill as completion
        r = m.record_finished(_Req(prompt_ids=[1] * 12, base_prompt_len=8))
        assert r["prompt_tokens"] == 8
        assert r["completion_tokens"] == 3 + 4

    def test_metrics_counters_booked_per_record(self):
        class _Counter:
            def __init__(self):
                self.calls = []

            def inc(self, v=1, **labels):
                self.calls.append((v, labels))

        class _Metrics:
            usage_tokens = _Counter()
            usage_records = _Counter()

        m = UsageMeter(metrics=_Metrics())
        m.record_finished(_Req(adapter_id="ad-b"))
        kinds = {c[1]["kind"]: c[0] for c in _Metrics.usage_tokens.calls}
        assert kinds == {"prompt": 8, "cached": 4, "completion": 3}
        assert all(c[1]["adapter"] == "ad-b" for c in _Metrics.usage_tokens.calls)
        assert _Metrics.usage_records.calls == [(1, {"tenant": "acme"})]

    def test_durable_meter_survives_reload(self, tmp_path):
        m = UsageMeter(ledger=UsageLedger(str(tmp_path), replica="r0"))
        m.record_finished(_Req())
        m.record_finished(_Req(trace="tr-2", tenant="globex"))
        m.close()
        records, _ = load_ledger_dir(str(tmp_path))
        assert {r["record_id"] for r in records} == {"tr-1", "tr-2"}
        assert all(r["replica"] == "r0" for r in records)


# ------------------------------------------------------------- offline report
class TestUsageReport:
    def _write_segment(self, path, records):
        with open(path, "w", encoding="utf-8") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")

    def test_merge_dedup_price_reconcile(self, tmp_path, capsys):
        a, b = tmp_path / "a", tmp_path / "b"
        a.mkdir(), b.mkdir()
        self._write_segment(a / "usage-r0-000000.jsonl",
                            [rec(0), rec(1, tenant="globex", adapter="ad-a")])
        # replica b booked tr-1's failed first attempt (mid-stream failover)
        # plus a torn line
        with open(b / "usage-r1-000000.open.jsonl", "w", encoding="utf-8") as f:
            f.write(json.dumps(rec(1, tenant="globex", adapter="ad-a",
                                   finish="engine_error", completion_tokens=2,
                                   useful_tokens=4)) + "\n")
            f.write('{"torn')
        code = usage_report.main([str(a), str(b), "--useful-total", "24",
                                  "--price-per-1k", "2.0"])
        out = capsys.readouterr().out
        assert code == 0
        assert "2 billed" in out
        assert "1 failover-superseded" in out
        assert "1 torn lines dropped" in out
        assert "reconciliation" in out and "-> ok" in out

    def test_double_bill_conflict_exits_1(self, tmp_path, capsys):
        d = tmp_path / "led"
        d.mkdir()
        self._write_segment(d / "usage-r0-000000.jsonl", [rec(0)])
        # the hand-corrupted case: same id, both successful, doubled tokens
        self._write_segment(d / "usage-r1-000000.jsonl",
                            [rec(0, prompt_tokens=20, completion_tokens=10)])
        code = usage_report.main([str(d)])
        assert code == 1
        assert "CONFLICT" in capsys.readouterr().out

    def test_identical_duplicates_collapse_silently(self, tmp_path):
        d = tmp_path / "led"
        d.mkdir()
        self._write_segment(d / "usage-r0-000000.jsonl", [rec(0)])
        self._write_segment(d / "usage-r1-000000.jsonl", [rec(0)])
        code = usage_report.main([str(d), "--json"])
        assert code == 0

    def test_reconciliation_divergence_beyond_slack_exits_1(self, tmp_path, capsys):
        d = tmp_path / "led"
        d.mkdir()
        self._write_segment(d / "usage-r0-000000.jsonl", [rec(0)])  # useful 12
        assert usage_report.main([str(d), "--useful-total", "20",
                                  "--slack", "8"]) == 0
        capsys.readouterr()
        code = usage_report.main([str(d), "--useful-total", "20", "--slack", "7"])
        assert code == 1
        assert "DIVERGED" in capsys.readouterr().out

    def test_json_output_matches_fold_shape(self, tmp_path, capsys):
        d = tmp_path / "led"
        d.mkdir()
        self._write_segment(d / "usage-r0-000000.jsonl",
                            [rec(0), rec(1, tenant="globex")])
        assert usage_report.main([str(d), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        agg = empty_aggregate()
        fold_record(agg, rec(0))
        fold_record(agg, rec(1, tenant="globex"))
        assert doc["usage"] == agg
