"""tier-1 enforcement of metric-catalog hygiene: tools/check_metrics.py must
lint the full serving + training catalog clean (HELP/TYPE present, valid
Prometheus text format)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
TOOL = os.path.join(REPO, "tools", "check_metrics.py")


class TestCheckMetrics:
    def test_catalog_lints_clean(self):
        proc = subprocess.run(
            [sys.executable, TOOL], capture_output=True, text=True, timeout=300,
            cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        line = next((ln for ln in reversed(proc.stdout.strip().splitlines())
                     if ln.startswith("{")), None)
        assert line is not None, f"no JSON output (rc={proc.returncode}): {proc.stderr[-2000:]}"
        report = json.loads(line)
        assert proc.returncode == 0 and report["ok"], report["problems"]
        # the serving + router + training catalogs are all present
        assert report["families"] >= 26

    def test_router_series_in_catalog(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import check_metrics
        finally:
            sys.path.pop(0)
        text = check_metrics.catalog_exposition()
        for name in ("paddlenlp_router_requests_total",
                     "paddlenlp_router_replica_healthy",
                     "paddlenlp_router_failovers_total",
                     "paddlenlp_router_rerouted_total",
                     "paddlenlp_router_route_decision_seconds",
                     "paddlenlp_router_health_polls_total",
                     "ckpt_last_commit_age_seconds"):
            assert f"# TYPE {name} " in text, f"{name} missing from lint catalog"

    def test_lint_flags_dirty_exposition(self, tmp_path):
        dump = tmp_path / "dump.txt"
        dump.write_text("# TYPE nohelp_total counter\nnohelp_total 1\nuntyped_thing 2\n")
        proc = subprocess.run(
            [sys.executable, TOOL, "--file", str(dump)],
            capture_output=True, text=True, timeout=300, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert len(report["problems"]) == 2
