"""Background HTTP exporter: /metrics + /health + /debug/trace off a daemon
thread, plus the Prometheus parse/lint helpers it feeds."""

import http.client
import json

import pytest

from paddlenlp_tpu.observability import (
    ObservabilityExporter,
    SpanTracer,
    histogram_quantile,
    lint_exposition,
    parse_prometheus_text,
)
from paddlenlp_tpu.serving.metrics import MetricsRegistry


@pytest.fixture()
def exporter():
    registry = MetricsRegistry()
    registry.counter("demo_requests_total", "Demo requests").inc(3)
    registry.histogram("demo_latency_seconds", "Demo latency").observe(0.02)
    tracer = SpanTracer(capacity=32)
    with tracer.span("phase", cat="demo"):
        pass
    exp = ObservabilityExporter(
        registry=registry, tracer=tracer, health_fn=lambda: {"step": 7})
    port = exp.start(port=0)
    yield exp, port
    exp.shutdown()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, body


class TestExporter:
    def test_metrics_endpoint(self, exporter):
        _, port = exporter
        status, body = _get(port, "/metrics")
        assert status == 200
        text = body.decode()
        assert "# TYPE demo_requests_total counter" in text
        assert "demo_requests_total 3" in text
        assert lint_exposition(text) == []

    def test_health_endpoint(self, exporter):
        _, port = exporter
        status, body = _get(port, "/health")
        payload = json.loads(body)
        assert status == 200 and payload["status"] == "ok" and payload["step"] == 7

    def test_debug_trace_endpoint(self, exporter):
        _, port = exporter
        status, body = _get(port, "/debug/trace")
        assert status == 200
        events = json.loads(body)["traceEvents"]
        assert any(e["name"] == "phase" and e["ph"] == "X" for e in events)

    def test_debug_spans_endpoint(self, exporter):
        _, port = exporter
        status, body = _get(port, "/debug/spans")
        assert status == 200
        assert json.loads(body.decode().splitlines()[0])["name"] == "phase"

    def test_404(self, exporter):
        _, port = exporter
        status, _ = _get(port, "/nope")
        assert status == 404

    def test_ring_overflow_is_accounted(self, exporter):
        # the bounded ring drops oldest spans silently; the drop count must
        # surface in /debug/trace responses AND as a counter on /metrics
        exp, port = exporter
        for i in range(40):  # capacity is 32: 8 spans fall off the back
            exp.tracer.instant(f"s{i}")
        status, body = _get(port, "/debug/trace")
        assert status == 200
        dropped = json.loads(body)["otherData"]["dropped_spans"]
        assert dropped == exp.tracer.dropped > 0
        status, body = _get(port, "/metrics")
        fams = parse_prometheus_text(body.decode())
        assert fams["paddlenlp_traces_dropped_total"].value() == dropped
        # counter only tops UP (monotone) across scrapes
        _get(port, "/metrics")
        fams = parse_prometheus_text(_get(port, "/metrics")[1].decode())
        assert fams["paddlenlp_traces_dropped_total"].value() == dropped


class TestPromParse:
    def test_parse_and_quantile_roundtrip(self):
        registry = MetricsRegistry()
        h = registry.histogram("rt_seconds", "round trip", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.6, 5.0):
            h.observe(v)
        registry.counter("hits_total", "hits", labelnames=("code",)).inc(4, code="200")
        fams = parse_prometheus_text(registry.expose())
        assert fams["hits_total"].value(code="200") == 4
        assert fams["rt_seconds"].type == "histogram"
        assert fams["rt_seconds"].value("rt_seconds_count") == 4
        # in-process percentile and scraped-quantile agree (same bucket math)
        assert histogram_quantile(fams["rt_seconds"], 0.5) == h.percentile(0.5)

    def test_label_values_roundtrip(self):
        registry = MetricsRegistry()
        c = registry.counter("hits_total", "hits", labelnames=("model",))
        for value in ('café', 'a"b', 'x\\y', 'line\nbreak'):
            c.inc(model=value)
        fams = parse_prometheus_text(registry.expose())
        for value in ('café', 'a"b', 'x\\y', 'line\nbreak'):
            assert fams["hits_total"].value(model=value) == 1, value

    def test_lint_catches_problems(self):
        assert lint_exposition("no_type_metric 1\n") == [
            "no_type_metric: samples without a # TYPE line"]
        missing_help = "# TYPE x counter\nx 1\n"
        assert any("missing # HELP" in p for p in lint_exposition(missing_help))
        bad_hist = ("# HELP h H\n# TYPE h histogram\n"
                    'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\nh_sum 1\nh_count 3\n')
        assert any("not cumulative" in p for p in lint_exposition(bad_hist))
        neg = "# HELP c C\n# TYPE c counter\nc -1\n"
        assert any("has value -1" in p for p in lint_exposition(neg))
