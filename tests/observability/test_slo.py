"""SLO plane unit tests (ISSUE 6): burn-rate math against synthetic
histograms, window baselining, counter-reset handling, and the
``paddlenlp_slo_*`` gauge series. Stdlib-only module — no jax, no engine."""

import pytest

from paddlenlp_tpu.observability import (
    SLOObjectives,
    SLOTracker,
    parse_prometheus_text,
    slo_inputs_from_families,
)
from paddlenlp_tpu.observability.slo import SLOInputs
from paddlenlp_tpu.serving.metrics import MetricsRegistry


def synthetic_exposition(stop=90.0, engine_error=5.0, abort=5.0,
                         buckets=((0.1, 80.0), (1.0, 95.0), ("+Inf", 100.0)),
                         count=100.0, replica=None):
    """Hand-built replica exposition: requests by status + a TTFT histogram."""
    lbl = f',replica="{replica}"' if replica else ""
    pre = f'replica="{replica}",' if replica else ""
    lines = [
        "# TYPE paddlenlp_serving_requests_total counter",
        f'paddlenlp_serving_requests_total{{status="stop"{lbl}}} {stop}',
        f'paddlenlp_serving_requests_total{{status="engine_error"{lbl}}} {engine_error}',
        f'paddlenlp_serving_requests_total{{status="abort"{lbl}}} {abort}',
        "# TYPE paddlenlp_serving_ttft_seconds histogram",
    ]
    for le, c in buckets:
        lines.append(f'paddlenlp_serving_ttft_seconds_bucket{{{pre}le="{le}"}} {c}')
    lines.append(f"paddlenlp_serving_ttft_seconds_count{{{lbl.lstrip(',')}}} {count}"
                 if replica else f"paddlenlp_serving_ttft_seconds_count {count}")
    lines.append(f"paddlenlp_serving_ttft_seconds_sum{{{lbl.lstrip(',')}}} 12.5"
                 if replica else "paddlenlp_serving_ttft_seconds_sum 12.5")
    return "\n".join(lines) + "\n"


class TestObjectives:
    def test_validation(self):
        with pytest.raises(ValueError):
            SLOObjectives(availability=1.0)
        with pytest.raises(ValueError):
            SLOObjectives(ttft_quantile=0.0)
        with pytest.raises(ValueError):
            SLOObjectives(ttft_threshold_s=0.0)

    def test_defaults_valid(self):
        obj = SLOObjectives()
        assert 0 < obj.availability < 1 and obj.ttft_threshold_s > 0


class TestInputsFromFamilies:
    def test_hand_computed_totals(self):
        fams = parse_prometheus_text(synthetic_exposition())
        inputs = slo_inputs_from_families(fams, SLOObjectives(ttft_threshold_s=1.0))
        # stop+engine_error+abort = 100 finished; engine_error spends budget,
        # stop/abort do not
        assert inputs.total == 100.0 and inputs.errors == 5.0
        # threshold 1.0 sits exactly on a bucket bound: good = 95, so 5 violations
        assert inputs.ttft_count == 100.0 and inputs.ttft_violations == 5.0

    def test_off_bucket_threshold_overcounts_violations(self):
        # threshold 0.5 between bounds 0.1 and 1.0: the next-LOWER bound is
        # used (good=80 -> 20 violations), over-counting — the safe side
        fams = parse_prometheus_text(synthetic_exposition())
        inputs = slo_inputs_from_families(fams, SLOObjectives(ttft_threshold_s=0.5))
        assert inputs.ttft_violations == 20.0

    def test_federated_labelsets_sum(self):
        # two replicas' series in one exposition (the federated case): totals
        # sum across the replica label, buckets grouped per replica labelset
        text = (synthetic_exposition(replica="r0").rstrip("\n") + "\n"
                + "\n".join(l for l in synthetic_exposition(replica="r1").splitlines()
                            if not l.startswith("#")) + "\n")
        fams = parse_prometheus_text(text)
        inputs = slo_inputs_from_families(fams, SLOObjectives(ttft_threshold_s=1.0))
        assert inputs.total == 200.0 and inputs.errors == 10.0
        assert inputs.ttft_count == 200.0 and inputs.ttft_violations == 10.0

    def test_empty_families(self):
        inputs = slo_inputs_from_families({}, SLOObjectives())
        assert inputs == SLOInputs()


class TestBurnRates:
    OBJ = SLOObjectives(availability=0.999, ttft_threshold_s=1.0, ttft_quantile=0.99)

    def test_lifetime_window_falls_back_to_zero_baseline(self):
        tr = SLOTracker(objectives=self.OBJ, windows_s=(60.0, 3600.0))
        tr.observe(SLOInputs(total=100, errors=1, ttft_count=100, ttft_violations=2),
                   now=1000.0)
        rep = tr.report(now=1000.0)
        for w in ("60s", "3600s"):  # no history: both windows see process start
            row = rep["windows"][w]
            assert row["availability"] == pytest.approx(0.99)
            # err rate 0.01 against a 0.001 budget: burning 10x
            assert row["availability_burn_rate"] == pytest.approx(10.0)
            assert row["ttft_violation_rate"] == pytest.approx(0.02)
            assert row["ttft_burn_rate"] == pytest.approx(2.0)

    def test_short_window_uses_recent_baseline(self):
        tr = SLOTracker(objectives=self.OBJ, windows_s=(60.0, 3600.0))
        tr.observe(SLOInputs(total=100, errors=1, ttft_count=100, ttft_violations=2),
                   now=1000.0)
        tr.observe(SLOInputs(total=200, errors=1, ttft_count=200, ttft_violations=2),
                   now=1070.0)
        rep = tr.report(now=1070.0)
        # 60s window baseline = the t=1000 point: 100 new requests, 0 new errors
        short = rep["windows"]["60s"]
        assert short["requests"] == 100.0
        assert short["availability"] == 1.0 and short["availability_burn_rate"] == 0.0
        assert short["ttft_burn_rate"] == 0.0
        # 3600s window still reaches past history: lifetime rates
        assert rep["windows"]["3600s"]["availability"] == pytest.approx(1 - 1 / 200)

    def test_empty_window_spends_no_budget(self):
        tr = SLOTracker(objectives=self.OBJ, windows_s=(60.0,))
        inputs = SLOInputs(total=50, errors=50, ttft_count=50, ttft_violations=50)
        tr.observe(inputs, now=0.0)
        tr.observe(inputs, now=120.0)  # no new traffic in the last 60s
        row = tr.report(now=120.0)["windows"]["60s"]
        assert row["requests"] == 0.0
        assert row["availability"] == 1.0 and row["availability_burn_rate"] == 0.0

    def test_counter_reset_drops_history(self):
        tr = SLOTracker(objectives=self.OBJ, windows_s=(60.0,))
        tr.observe(SLOInputs(total=1000, errors=900), now=0.0)
        # fleet totals shrank (replica restart) and STAYED low: the second
        # consecutive shrunk observation confirms the reset and drops history
        tr.observe(SLOInputs(total=10, errors=0), now=10.0)
        tr.observe(SLOInputs(total=12, errors=0), now=20.0)
        row = tr.report(now=20.0)["windows"]["60s"]
        assert row["requests"] == 12.0 and row["availability"] == 1.0

    def test_masked_replica_reset_clamps_not_inflates(self):
        tr = SLOTracker(objectives=self.OBJ, windows_s=(60.0,))
        tr.observe(SLOInputs(total=100, errors=5, ttft_count=100,
                             ttft_violations=5), now=0.0)
        # one replica reset (its 5 errors vanished) masked by another's
        # growth: total still rose, so reset detection cannot fire — the
        # negative error delta must clamp to 0, not report availability > 1
        tr.observe(SLOInputs(total=250, errors=0, ttft_count=250,
                             ttft_violations=0), now=10.0)
        row = tr.report(now=10.0)["windows"]["60s"]
        assert row["availability"] == 1.0
        assert row["availability_burn_rate"] == 0.0
        assert row["ttft_burn_rate"] == 0.0

    def test_transient_scrape_dip_does_not_wipe_history(self):
        tr = SLOTracker(objectives=self.OBJ, windows_s=(3600.0,))
        tr.observe(SLOInputs(total=3000, errors=30), now=0.0)
        # one replica's scrape blipped out of the merge for a single
        # observation: dropped, NOT treated as a counter reset
        tr.observe(SLOInputs(total=2000, errors=20), now=10.0)
        tr.observe(SLOInputs(total=3300, errors=33), now=20.0)
        row = tr.report(now=20.0)["windows"]["3600s"]
        assert row["requests"] == 3300.0  # lifetime baseline survived the blip
        assert abs(row["availability"] - (1.0 - 33.0 / 3300.0)) < 1e-9

    def test_empty_tracker_report(self):
        rep = SLOTracker(objectives=self.OBJ).report()
        assert rep["windows"] == {}

    def test_history_pruning_keeps_long_window_baseline(self):
        tr = SLOTracker(objectives=self.OBJ, windows_s=(60.0,))
        for i in range(200):
            tr.observe(SLOInputs(total=float(i), errors=0.0), now=float(i))
        # pruned to ~window depth, but one at-or-before-horizon point remains
        assert len(tr._history) < 200
        assert tr._history[0][0] <= 199.0 - 60.0

    def test_validation(self):
        with pytest.raises(ValueError):
            SLOTracker(windows_s=())
        with pytest.raises(ValueError):
            SLOTracker(windows_s=(0.0,))


class TestGaugeSeries:
    def test_slo_gauges_land_in_registry(self):
        reg = MetricsRegistry()
        tr = SLOTracker(objectives=SLOObjectives(availability=0.999),
                        windows_s=(60.0,), registry=reg)
        tr.observe(SLOInputs(total=100, errors=1, ttft_count=100, ttft_violations=2),
                   now=0.0)
        tr.report(now=0.0)
        fams = parse_prometheus_text(reg.expose())
        avail = fams["paddlenlp_slo_availability"].value(window="60s")
        assert avail == pytest.approx(0.99)
        burn = fams["paddlenlp_slo_availability_burn_rate"].value(window="60s")
        assert burn == pytest.approx(10.0)
        assert fams["paddlenlp_slo_availability_objective"].value() == pytest.approx(0.999)
