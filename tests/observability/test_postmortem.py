"""Postmortem bundles (ISSUE 13): the dumper writes self-contained JSON
bundles (events + spans + health + metrics + config), rate-limits auto
triggers, gates them on PDNLP_TPU_POSTMORTEM_DIR, and the offline analyzer
(tools/postmortem.py) reconstructs per-request cross-tier timelines from
them. SLO fast burns fire the tracker's trigger hook."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from paddlenlp_tpu.observability import (  # noqa: E402
    FlightRecorder,
    PostmortemDumper,
    SLOObjectives,
    SLOTracker,
    SpanTracer,
    handle_postmortem_request,
)
from paddlenlp_tpu.observability.postmortem import ENV_DIR  # noqa: E402
from paddlenlp_tpu.observability.slo import SLOInputs  # noqa: E402
from paddlenlp_tpu.serving.metrics import MetricsRegistry  # noqa: E402
from tools.postmortem import (  # noqa: E402
    attribution_for,
    load_bundles,
    main as postmortem_main,
    merged_events,
    render_timeline,
    request_ids,
    timeline_for,
)


def make_dumper(tmp_path, tier="replica", **kw):
    registry = MetricsRegistry()
    registry.counter("demo_total", "a demo counter").inc(3)
    tracer = SpanTracer(capacity=64)
    recorder = FlightRecorder(capacity=64, enabled=True)
    kw.setdefault("out_dir", str(tmp_path))
    kw.setdefault("min_interval_s", 30.0)
    dumper = PostmortemDumper(
        registry=registry, tracer=tracer, recorder=recorder, tier=tier,
        health_fn=kw.pop("health_fn", lambda: {"loop_state": "running"}),
        config_fn=kw.pop("config_fn", lambda: {"max_batch_size": 4}), **kw)
    return dumper, recorder, tracer


class TestDumper:
    def test_bundle_is_self_contained_valid_json(self, tmp_path):
        dumper, recorder, tracer = make_dumper(tmp_path)
        recorder.record("admit.accept", req_id=0, trace="req-0", slot=0)
        with tracer.span("prefill", cat="engine", trace="req-0"):
            pass
        path = dumper.dump("supervisor_degraded", detail={"error": "boom"})
        assert path is not None and os.path.isfile(path)
        assert os.path.basename(path).startswith("postmortem-replica-supervisor_degraded-")
        bundle = json.load(open(path))
        assert bundle["version"] == 1 and bundle["tier"] == "replica"
        assert bundle["trigger"] == "supervisor_degraded"
        assert bundle["detail"] == {"error": "boom"}
        assert bundle["events"][0]["name"] == "admit.accept"
        assert any(s["name"] == "prefill" for s in bundle["spans"])
        assert bundle["health"]["loop_state"] == "running"
        assert bundle["config"]["max_batch_size"] == 4
        assert "demo_total 3" in bundle["metrics"]
        assert dumper.dumps == 1 and dumper.last_path == path

    def test_rate_limit_suppresses_auto_but_not_forced(self, tmp_path):
        dumper, _, _ = make_dumper(tmp_path, min_interval_s=3600.0)
        assert dumper.dump("supervisor_degraded") is not None
        assert dumper.dump("supervisor_degraded") is None  # inside the window
        assert dumper.suppressed == 1
        assert dumper.dump("on_demand", force=True) is not None  # force bypasses
        assert dumper.dumps == 2

    def test_forced_dump_does_not_consume_rate_limit_slot(self, tmp_path):
        # an operator curl (or periodic monitoring scrape) of the on-demand
        # endpoint must never suppress the NEXT incident's auto bundle
        dumper, _, _ = make_dumper(tmp_path, min_interval_s=3600.0)
        assert dumper.dump("on_demand", force=True) is not None
        assert dumper.dump("supervisor_degraded") is not None  # auto still fires
        assert dumper.suppressed == 0

    def test_failed_write_releases_rate_limit_slot(self, tmp_path):
        blocker = tmp_path / "blocked"
        blocker.write_text("a file where the out dir should be")
        dumper, _, _ = make_dumper(tmp_path, min_interval_s=3600.0,
                                   out_dir=str(blocker))
        assert dumper.dump("supervisor_degraded") is None  # makedirs fails
        dumper._out_dir = str(tmp_path)
        # the failed attempt must not have claimed the 1h window
        assert dumper.dump("supervisor_degraded") is not None

    def test_filenames_unique_across_dumpers_same_second(self, tmp_path):
        d1, _, _ = make_dumper(tmp_path)
        d2, _, _ = make_dumper(tmp_path)
        p1 = d1.dump("supervisor_degraded", force=True)
        p2 = d2.dump("supervisor_degraded", force=True)
        assert p1 != p2 and os.path.isfile(p1) and os.path.isfile(p2)

    def test_trigger_label_sanitized_in_filename(self, tmp_path):
        dumper, _, _ = make_dumper(tmp_path)
        status, _, body = handle_postmortem_request(
            "/debug/postmortem?trigger=a/b%20drill", dumper)
        assert status == 200
        doc = json.loads(body)
        assert os.path.isfile(doc["path"])
        assert "/" not in os.path.basename(doc["path"])
        # the bundle keeps the original label; only the filename is sanitized
        assert json.load(open(doc["path"]))["trigger"] == "a/b drill"

    def test_auto_dump_gated_on_env_dir(self, tmp_path, monkeypatch):
        monkeypatch.delenv(ENV_DIR, raising=False)
        dumper = PostmortemDumper(registry=MetricsRegistry(),
                                  tracer=SpanTracer(capacity=8),
                                  recorder=FlightRecorder(capacity=8))
        # no out_dir, no env var: auto triggers are opt-in -> suppressed
        assert dumper.dump("supervisor_degraded") is None
        assert dumper.suppressed == 1
        # env var set: the same trigger writes
        monkeypatch.setenv(ENV_DIR, str(tmp_path))
        path = dumper.dump("slot_quarantine")
        assert path is not None and path.startswith(str(tmp_path))

    def test_broken_providers_do_not_kill_the_dump(self, tmp_path):
        def bad():
            raise RuntimeError("provider exploded")

        dumper, _, _ = make_dumper(tmp_path, health_fn=bad, config_fn=bad)
        path = dumper.dump("drain_evict", force=True)
        bundle = json.load(open(path))
        assert "provider exploded" in bundle["health"]["error"]
        assert "provider exploded" in bundle["config"]["error"]

    def test_http_handler_contract(self, tmp_path):
        dumper, _, _ = make_dumper(tmp_path)
        assert handle_postmortem_request("/not/postmortem", dumper) is None
        status, ctype, body = handle_postmortem_request(
            "/debug/postmortem?trigger=drill", dumper)
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert doc["trigger"] == "drill" and os.path.isfile(doc["path"])
        assert json.load(open(doc["path"]))["trigger"] == "drill"


class TestSLOFastBurn:
    def _observe_burning(self, tracker, errors_frac):
        tracker.observe(SLOInputs(total=0, errors=0, ttft_count=0,
                                  ttft_violations=0), now=1000.0)
        tracker.observe(SLOInputs(total=100, errors=100 * errors_frac,
                                  ttft_count=100, ttft_violations=0), now=1030.0)

    def test_hook_fires_on_fast_burn(self):
        tracker = SLOTracker(objectives=SLOObjectives(availability=0.999),
                             windows_s=(60.0, 300.0), fast_burn_threshold=10.0)
        fired = []
        tracker.on_fast_burn = lambda kind, burn, window: fired.append(
            (kind, burn, window))
        self._observe_burning(tracker, errors_frac=0.5)  # burn 500x budget
        tracker.report(now=1030.0)
        assert fired and fired[0][0] == "availability"
        assert fired[0][1] >= 10.0 and fired[0][2] == "60s"

    def test_hook_quiet_below_threshold_and_guarded(self):
        tracker = SLOTracker(objectives=SLOObjectives(availability=0.9),
                             windows_s=(60.0,), fast_burn_threshold=10.0)
        fired = []
        tracker.on_fast_burn = lambda *a: fired.append(a)
        self._observe_burning(tracker, errors_frac=0.0)
        tracker.report(now=1030.0)
        assert not fired
        # a broken hook never breaks report()
        tracker2 = SLOTracker(objectives=SLOObjectives(availability=0.999),
                              windows_s=(60.0,), fast_burn_threshold=1.0)

        def boom(*a):
            raise RuntimeError("hook exploded")

        tracker2.on_fast_burn = boom
        self._observe_burning(tracker2, errors_frac=0.5)
        assert "windows" in tracker2.report(now=1030.0)


class TestOfflineAnalyzer:
    """tools/postmortem.py over synthetic two-tier bundles: the router's
    hedge/reroute events and the replica's engine events join on one trace id
    into a monotonic timeline, and the attribution row is found."""

    def _two_tier_bundles(self, tmp_path):
        # one shared recorder = the in-process-fleet case; the router bundle
        # and replica bundle snapshot the same ring at different moments
        recorder = FlightRecorder(capacity=64)
        tracer = SpanTracer(capacity=64)
        recorder.record("router.reroute", trace="rtr-7", replica="a")
        recorder.record("admit.accept", req_id=0, trace="rtr-7", slot=0)
        recorder.record("chunk.grant", req_id=0, trace="rtr-7", tokens=8)
        recorder.record("router.hedge_fire", trace="rtr-7", replica="b")
        recorder.record("router.hedge_commit", trace="rtr-7", replica="b",
                        outcome="hedge_won")
        recorder.record("admit.accept", req_id=1, trace="rtr-8", slot=1)
        tracer.add_span("prefill", tracer.now() - 0.01, 0.01, cat="engine",
                        trace="rtr-7")
        row = {"trace": "rtr-7", "req_id": 0, "finish_reason": "length",
               "arrival_t": 100.0, "finish_t": 100.5,
               "attribution": {"queue": 0.1, "admission_gate": 0.0,
                               "prefill": 0.2, "chunk_stall": 0.0,
                               "migration_wait": 0.0, "decode": 0.2}}
        registry = MetricsRegistry()
        router = PostmortemDumper(registry=registry, tracer=tracer,
                                  recorder=recorder, tier="router",
                                  out_dir=str(tmp_path),
                                  health_fn=lambda: {"policy": "least_loaded"})
        replica = PostmortemDumper(registry=registry, tracer=tracer,
                                   recorder=recorder, tier="replica",
                                   out_dir=str(tmp_path),
                                   health_fn=lambda: {"recent_finished": [row]})
        return [router.dump("drain_evict", force=True),
                replica.dump("on_demand", force=True)]

    def test_cross_tier_timeline_joined_and_monotonic(self, tmp_path):
        paths = self._two_tier_bundles(tmp_path)
        bundles = load_bundles(paths)
        # duplicate events across the two overlapping bundles collapse
        assert len(merged_events(bundles)) == 6
        entries = timeline_for(bundles, "rtr-7")
        names = [e["name"] for e in entries if e["kind"] == "event"]
        assert names == ["router.reroute", "admit.accept", "chunk.grant",
                         "router.hedge_fire", "router.hedge_commit"]
        tiers = {e["name"]: e["tier"] for e in entries if e["kind"] == "event"}
        assert tiers["router.hedge_fire"] == "router"
        assert tiers["admit.accept"] == "engine"
        assert any(e["kind"] == "span" and e["name"] == "prefill" for e in entries)
        ts = [e["t"] for e in entries]
        assert ts == sorted(ts)  # monotonic timeline
        # the other request's events stay out
        assert not any(e.get("req_id") == 1 for e in entries)
        lines = render_timeline(entries)
        assert len(lines) == len(entries)
        assert "router.hedge_commit" in "".join(lines)

    def test_request_listing_and_attribution(self, tmp_path):
        paths = self._two_tier_bundles(tmp_path)
        bundles = load_bundles(paths)
        ids = request_ids(bundles)
        assert set(ids) == {"rtr-7", "rtr-8"}
        assert ids["rtr-7"]["router"] == 3 and ids["rtr-7"]["engine"] == 2
        row = attribution_for(bundles, "rtr-7")
        assert row is not None
        assert abs(sum(row["attribution"].values()) - 0.5) < 1e-9
        assert attribution_for(bundles, "rtr-404") is None

    def test_cli_modes(self, tmp_path, capsys):
        paths = self._two_tier_bundles(tmp_path)
        assert postmortem_main(paths) == 0
        out = capsys.readouterr().out
        assert "tier=router" in out and "trigger=drain_evict" in out
        assert postmortem_main(paths + ["--list"]) == 0
        out = capsys.readouterr().out
        assert "rtr-7" in out and "rtr-8" in out
        assert postmortem_main(paths + ["--req", "rtr-7"]) == 0
        out = capsys.readouterr().out
        assert "decision trail for rtr-7" in out
        assert "router.hedge_fire" in out and "admit.accept" in out
        assert "latency attribution" in out and "migration_wait" in out
        assert postmortem_main([]) == 2

    def test_traceless_listing_key_round_trips_through_req(self, tmp_path):
        # a trace-less event is listed as "req_id:N" — that exact selector
        # must work with --req (the tool's own output is a valid input)
        recorder = FlightRecorder(capacity=8)
        recorder.record("migrate.defer", req_id=5, reason="decode_pressure")
        dumper = PostmortemDumper(registry=MetricsRegistry(),
                                  tracer=SpanTracer(capacity=8),
                                  recorder=recorder, out_dir=str(tmp_path))
        bundles = load_bundles([dumper.dump("on_demand", force=True)])
        assert "req_id:5" in request_ids(bundles)
        entries = timeline_for(bundles, "req_id:5")
        assert [e["name"] for e in entries] == ["migrate.defer"]

    def test_pid_collision_does_not_collapse_distinct_events(self, tmp_path):
        # two bundles from different processes that happen to share a pid:
        # same seq numbers but different timestamps must NOT dedup
        paths = self._two_tier_bundles(tmp_path)
        bundles = load_bundles(paths)
        other = json.loads(json.dumps(bundles[0]))  # deep copy, same "pid"
        for ev in other["events"]:
            ev["t"] += 50.0  # a different process's clock
        assert len(merged_events(bundles + [other])) == 12

    def test_req_flag_without_value_is_usage_error(self, tmp_path, capsys):
        paths = self._two_tier_bundles(tmp_path)
        assert postmortem_main(paths + ["--req"]) == 2

    def test_rejects_non_bundle(self, tmp_path):
        p = tmp_path / "junk.json"
        p.write_text(json.dumps({"hello": 1}))
        with pytest.raises(ValueError, match="not a postmortem bundle"):
            load_bundles([str(p)])
