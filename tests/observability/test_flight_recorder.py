"""Flight recorder (ISSUE 13): bounded ring of catalog-validated decision
events, thread-safe, with a disabled fast path that records nothing and
allocates nothing inside the recorder module."""

import threading

import pytest

from paddlenlp_tpu.observability import (
    EVENT_CATALOG,
    EVENT_REASONS,
    FlightRecorder,
)
from paddlenlp_tpu.observability import flight_recorder as fr_mod


class TestRecording:
    def test_event_fields_and_to_dict(self):
        rec = FlightRecorder(capacity=16, enabled=True)
        rec.record("admit.accept", req_id=3, trace="req-3", slot=1,
                   prompt_len=7, cached_tokens=4)
        (ev,) = rec.snapshot()
        assert ev.name == "admit.accept" and ev.seq == 1
        assert ev.req_id == 3 and ev.trace == "req-3"
        d = ev.to_dict()
        assert d["slot"] == 1 and d["cached_tokens"] == 4 and d["t"] > 0

    def test_unknown_name_and_bad_reason_fail_loudly(self):
        rec = FlightRecorder(capacity=64)
        with pytest.raises(ValueError, match="unknown decision event"):
            rec.record("not.a.thing")
        with pytest.raises(ValueError, match="not in its catalog enum"):
            rec.record("admit.defer", reason="because")
        # every declared reason is accepted for its event
        for name, reasons in EVENT_REASONS.items():
            for reason in reasons:
                rec.record(name, reason=reason)
        assert len(rec) == sum(len(v) for v in EVENT_REASONS.values())

    def test_reason_enums_subset_of_catalog(self):
        assert set(EVENT_REASONS) <= set(EVENT_CATALOG)

    def test_ring_bound_and_dropped_counter(self):
        rec = FlightRecorder(capacity=8)
        for i in range(20):
            rec.record("chunk.grant", req_id=i, tokens=1)
        assert len(rec) == 8
        assert rec.dropped == 12
        # oldest fell off: the surviving seqs are the last 8
        assert [e.seq for e in rec.snapshot()] == list(range(13, 21))
        rec.clear()
        assert len(rec) == 0 and rec.dropped == 0
        rec.record("chunk.grant", req_id=99, tokens=1)
        assert rec.snapshot()[0].seq == 21  # seq survives clear (cursor contract)

    def test_snapshot_filters(self):
        rec = FlightRecorder(capacity=64)
        rec.record("admit.accept", req_id=1, trace="rtr-1", slot=0)
        rec.record("admit.accept", req_id=2, trace="rtr-2", slot=1)
        rec.record("router.reroute", trace="rtr-1", replica="a")
        rec.record("preempt", req_id=1, trace="rtr-1", reason="decode_growth")
        assert [e.name for e in rec.snapshot(trace="rtr-1")] == \
            ["admit.accept", "router.reroute", "preempt"]
        assert [e.name for e in rec.snapshot(req_id=2)] == ["admit.accept"]
        assert [e.name for e in rec.snapshot(name_prefix="router.")] == \
            ["router.reroute"]
        cursor = rec.snapshot()[1].seq
        assert [e.name for e in rec.snapshot(since_seq=cursor)] == \
            ["router.reroute", "preempt"]

    def test_timestamps_monotonic(self):
        rec = FlightRecorder(capacity=64)
        for _ in range(32):
            rec.record("sched.reject", reason="saturated")
        ts = [e.t for e in rec.snapshot()]
        assert ts == sorted(ts)

    def test_thread_safety_no_loss_under_capacity(self):
        rec = FlightRecorder(capacity=4096)

        def worker(base):
            for i in range(100):
                rec.record("chunk.grant", req_id=base + i, tokens=1)

        threads = [threading.Thread(target=worker, args=(1000 * k,)) for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        events = rec.snapshot()
        assert len(events) == 800
        assert sorted(e.seq for e in events) == list(range(1, 801))


class TestDisabledPath:
    def test_records_nothing(self):
        rec = FlightRecorder(capacity=16, enabled=False)
        for _ in range(100):
            rec.record("admit.accept", req_id=1, slot=0)
        assert len(rec) == 0 and rec.dropped == 0
        # and validation is skipped entirely (the fast path returns first)
        rec.record("not.even.a.name")
        assert len(rec) == 0

    def test_allocates_nothing_in_the_recorder(self):
        """The disabled record() path must not retain allocations — one
        attribute read, return. Measured as net allocated-block growth over
        500 calls (transient call-site kwargs are freed immediately), with an
        enabled-recorder contrast proving the measurement detects retention."""
        import gc
        import sys

        rec = FlightRecorder(capacity=600, enabled=False)
        rec.record("admit.accept", req_id=1)  # warm any lazy state
        gc.collect()
        base = sys.getallocatedblocks()
        for i in range(500):
            rec.record("admit.accept", req_id=i, slot=0, prompt_len=3)
        gc.collect()
        grown_disabled = sys.getallocatedblocks() - base
        assert len(rec) == 0
        # contrast: the SAME loop with recording on retains ~1 event each
        rec.set_enabled(True)
        gc.collect()
        base = sys.getallocatedblocks()
        for i in range(500):
            rec.record("admit.accept", req_id=i, slot=0, prompt_len=3)
        gc.collect()
        grown_enabled = sys.getallocatedblocks() - base
        assert len(rec) == 500
        assert grown_enabled >= 500  # the measurement sees real retention ...
        assert grown_disabled <= 8, grown_disabled  # ... and the disabled path has none

    def test_env_gating(self, monkeypatch):
        monkeypatch.setenv(fr_mod.ENV_VAR, "0")
        assert FlightRecorder().enabled is False
        monkeypatch.setenv(fr_mod.ENV_VAR, "false")
        assert FlightRecorder().enabled is False
        monkeypatch.setenv(fr_mod.ENV_VAR, "1")
        assert FlightRecorder().enabled is True
        monkeypatch.delenv(fr_mod.ENV_VAR)
        assert FlightRecorder().enabled is True  # default on

    def test_set_enabled_round_trip(self):
        rec = FlightRecorder(capacity=4, enabled=True)
        rec.set_enabled(False)
        rec.record("preempt", req_id=1, reason="decode_growth")
        assert len(rec) == 0
        rec.set_enabled(True)
        rec.record("preempt", req_id=1, reason="decode_growth")
        assert len(rec) == 1


class TestCatalogHygiene:
    def test_every_entry_documented(self):
        for name, doc in EVENT_CATALOG.items():
            assert len(doc.strip()) >= 15, name
