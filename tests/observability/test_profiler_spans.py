"""Satellite: one profiler flag produces BOTH a jax.profiler device trace and
a host-side span timeline for the same step window."""

import json
import os

import pytest

from paddlenlp_tpu.observability import SpanTracer
from paddlenlp_tpu.utils.profiler import ProfilerOptions, ProfilerStepper


class TestProfilerSpanWindow:
    def test_window_dumps_span_timeline(self, tmp_path):
        path = str(tmp_path / "prof")
        tracer = SpanTracer(capacity=128)
        stepper = ProfilerStepper(
            ProfilerOptions(batch_range=(1, 3), profile_path=path), tracer=tracer)
        tracer.instant("before_window", cat="test")  # outside: must be excluded
        for step in range(5):
            stepper.step(step)
            with tracer.span(f"step{step}", cat="test"):
                pass
        timeline = os.path.join(path, "span_timeline.json")
        assert os.path.isdir(path), "jax.profiler trace dir missing"
        assert os.path.exists(timeline)
        with open(timeline) as f:
            events = json.load(f)["traceEvents"]
        names = {e["name"] for e in events}
        assert "profiler_window_start" in names
        assert "profiler_window_stop" in names
        assert {"step1", "step2"} <= names  # spans inside [1, 3)
        assert "before_window" not in names
        with open(os.path.join(path, "spans.jsonl")) as f:
            for line in f.read().strip().splitlines():
                json.loads(line)

    def test_close_flushes_open_window(self, tmp_path):
        path = str(tmp_path / "prof2")
        tracer = SpanTracer(capacity=128)
        stepper = ProfilerStepper(
            ProfilerOptions(batch_range=(0, 100), profile_path=path), tracer=tracer)
        stepper.step(0)
        with tracer.span("inside", cat="test"):
            pass
        stepper.close()
        with open(os.path.join(path, "span_timeline.json")) as f:
            names = {e["name"] for e in json.load(f)["traceEvents"]}
        assert "inside" in names

    def test_parse_rejects_bad_ranges(self):
        with pytest.raises(ValueError):
            ProfilerOptions.parse("batch_range=[5,2]")
        opts = ProfilerOptions.parse("batch_range=[10,20];profile_path=/tmp/x")
        assert opts.batch_range == (10, 20) and opts.profile_path == "/tmp/x"
