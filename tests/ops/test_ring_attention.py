"""Ring attention (cp) tests: parity with full attention, zigzag layout, gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.ops.flash_attention import dot_product_attention
from paddlenlp_tpu.ops.ring_attention import (
    ring_self_attention,
    zigzag_positions,
    zigzag_split,
    zigzag_unsplit,
)
from paddlenlp_tpu.parallel import MeshConfig, create_mesh, use_mesh


def make_qkv(B=2, S=32, N=4, K=2, H=16, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, S, N, H)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, K, H)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, K, H)), jnp.float32)
    return q, k, v


class TestRingParity:
    def test_causal_parity(self, eight_devices):
        mesh = create_mesh(MeshConfig(dp=2, cp=4))
        q, k, v = make_qkv()
        ref = dot_product_attention(q, k, v, causal=True)
        with use_mesh(mesh):
            out = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)

    def test_zigzag_layout_parity(self, eight_devices):
        """Ring attention on the zigzag-permuted sequence == full attention
        (positions carry the absolute order)."""
        mesh = create_mesh(MeshConfig(cp=4))
        q, k, v = make_qkv(B=1, S=32)
        ref = dot_product_attention(q, k, v, causal=True)
        qz, kz, vz = (zigzag_split(x, 4) for x in (q, k, v))
        pos = zigzag_positions(32, 4)
        with use_mesh(mesh):
            out_z = jax.jit(lambda a, b, c: ring_self_attention(a, b, c, mesh, positions=pos))(qz, kz, vz)
        out = zigzag_unsplit(out_z, 4)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)

    def test_gradients_flow(self, eight_devices):
        """Reverse-mode AD through the ring (the reference hand-writes this bwd)."""
        mesh = create_mesh(MeshConfig(cp=4))
        q, k, v = make_qkv(B=1, S=16, N=2, K=2, H=8)

        def loss_ring(q, k, v):
            return ring_self_attention(q, k, v, mesh).sum()

        def loss_ref(q, k, v):
            return dot_product_attention(q, k, v, causal=True).astype(jnp.float32).sum()

        with use_mesh(mesh):
            g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)

    def test_zigzag_roundtrip(self):
        x = jnp.arange(64).reshape(1, 64)
        z = zigzag_split(x, 4, axis=1)
        back = zigzag_unsplit(z, 4, axis=1)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
