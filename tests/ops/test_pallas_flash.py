"""Pallas flash attention kernel (interpret mode on CPU): parity + gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.ops.flash_attention import dot_product_attention
from paddlenlp_tpu.ops.pallas.flash_attention import flash_attention


def qkv(B=2, T=128, N=4, K=2, H=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.standard_normal((B, T, N, H)), dtype),
            jnp.asarray(rng.standard_normal((B, T, K, H)), dtype),
            jnp.asarray(rng.standard_normal((B, T, K, H)), dtype))


class TestPallasFlash:
    def test_causal_parity(self):
        q, k, v = qkv()
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        out = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_non_causal_parity(self):
        q, k, v = qkv(T=256)
        ref = dot_product_attention(q, k, v, causal=False, use_pallas=False)
        out = flash_attention(q, k, v, causal=False, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gqa_no_repeat(self):
        q, k, v = qkv(N=8, K=2)
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        out = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_multi_kv_blocks(self):
        """T > block sizes: the online-softmax accumulation across kv blocks."""
        q, k, v = qkv(B=1, T=512)
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        out = flash_attention(q, k, v, block_q=128, block_kv=128, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_bf16(self):
        q, k, v = qkv(dtype=jnp.bfloat16)
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        out = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32),
                                   atol=3e-2)

    def test_gradients_match_math_path(self):
        q, k, v = qkv(B=1, T=128, N=2, K=2, H=64)

        def f_pallas(q, k, v):
            return flash_attention(q, k, v, interpret=True).sum()

        def f_ref(q, k, v):
            return dot_product_attention(q, k, v, causal=True, use_pallas=False).astype(jnp.float32).sum()

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)

    def test_dispatcher_forced(self):
        """use_pallas=True routes through the kernel (interpret off-TPU) and matches."""
        q, k, v = qkv()
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        out = dot_product_attention(q, k, v, causal=True, use_pallas=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_ragged_kv_length_masked(self):
        """S not a multiple of block_kv: padding columns must not leak into softmax."""
        q, k, v = qkv(B=1, T=160, N=2, K=2)  # 160 = 128 + 32
        ref = dot_product_attention(q, k, v, causal=False, use_pallas=False)
        out = flash_attention(q, k, v, causal=False, block_kv=128, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_segment_ids_parity(self):
        """Packed-batch (ZeroPadding/flashmask) masking inside the kernel."""
        q, k, v = qkv(B=2, T=128)
        seg = jnp.asarray(np.repeat([[0, 1, 2, 3]], 2, axis=0).repeat(32, axis=1))  # 4 segments of 32
        ref = dot_product_attention(q, k, v, causal=True, segment_ids=seg, use_pallas=False)
        out = flash_attention(q, k, v, segment_ids=seg, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_sliding_window_parity(self):
        q, k, v = qkv(B=1, T=256)
        ref = dot_product_attention(q, k, v, causal=True, window=64, use_pallas=False)
        out = flash_attention(q, k, v, window=64, block_q=64, block_kv=64, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gradients_gqa_segments(self):
        """Pallas bwd kernels: GQA group-sum + segment masking, vs math-path grads."""
        q, k, v = qkv(B=1, T=128, N=4, K=2, H=64, seed=3)
        seg = jnp.asarray(np.repeat([[0, 1]], 1, axis=0).repeat(64, axis=1))

        def f_pallas(q, k, v):
            return (flash_attention(q, k, v, segment_ids=seg, interpret=True) ** 2).sum()

        def f_ref(q, k, v):
            return (dot_product_attention(q, k, v, causal=True, segment_ids=seg,
                                          use_pallas=False).astype(jnp.float32) ** 2).sum()

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3)

    def test_gradients_window(self):
        q, k, v = qkv(B=1, T=128, N=2, K=2, H=64, seed=5)

        def f_pallas(q, k, v):
            return flash_attention(q, k, v, window=32, interpret=True).sum()

        def f_ref(q, k, v):
            return dot_product_attention(q, k, v, causal=True, window=32,
                                         use_pallas=False).astype(jnp.float32).sum()

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3)

    def test_sharded_dispatch_parity(self, eight_devices):
        """use_pallas under a dp x tp mesh: the shard_map wrapper must reproduce
        the unsharded kernel output (values AND grads)."""
        from paddlenlp_tpu.parallel import MeshConfig, create_mesh, use_mesh

        q, k, v = qkv(B=2, T=128, N=4, K=4)
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        mesh = create_mesh(MeshConfig(dp=2, tp=4))
        with use_mesh(mesh):
            out = jax.jit(lambda q, k, v: dot_product_attention(q, k, v, causal=True, use_pallas=True))(q, k, v)
            g = jax.jit(jax.grad(lambda q, k, v: dot_product_attention(
                q, k, v, causal=True, use_pallas=True).astype(jnp.float32).sum(), argnums=0))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
        g_ref = jax.grad(lambda q, k, v: dot_product_attention(
            q, k, v, causal=True, use_pallas=False).astype(jnp.float32).sum(), argnums=0)(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=5e-4, rtol=1e-3)

    def test_causal_cross_length_rejected(self):
        q, _, _ = qkv(T=64)
        _, k, v = qkv(T=128)
        with pytest.raises(ValueError, match="requires T == S"):
            flash_attention(q, k, v, causal=True, interpret=True)
