"""Pallas flash attention kernel (interpret mode on CPU): parity + gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.ops.flash_attention import dot_product_attention
from paddlenlp_tpu.ops.pallas.flash_attention import flash_attention


def qkv(B=2, T=128, N=4, K=2, H=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.standard_normal((B, T, N, H)), dtype),
            jnp.asarray(rng.standard_normal((B, T, K, H)), dtype),
            jnp.asarray(rng.standard_normal((B, T, K, H)), dtype))


class TestPallasFlash:
    def test_causal_parity(self):
        q, k, v = qkv()
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        out = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_non_causal_parity(self):
        q, k, v = qkv(T=256)
        ref = dot_product_attention(q, k, v, causal=False, use_pallas=False)
        out = flash_attention(q, k, v, causal=False, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gqa_no_repeat(self):
        q, k, v = qkv(N=8, K=2)
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        out = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_multi_kv_blocks(self):
        """T > block sizes: the online-softmax accumulation across kv blocks."""
        q, k, v = qkv(B=1, T=512)
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        out = flash_attention(q, k, v, block_q=128, block_kv=128, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_bf16(self):
        q, k, v = qkv(dtype=jnp.bfloat16)
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        out = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32),
                                   atol=3e-2)

    def test_gradients_match_math_path(self):
        q, k, v = qkv(B=1, T=128, N=2, K=2, H=64)

        def f_pallas(q, k, v):
            return flash_attention(q, k, v, interpret=True).sum()

        def f_ref(q, k, v):
            return dot_product_attention(q, k, v, causal=True, use_pallas=False).astype(jnp.float32).sum()

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)

    def test_dispatcher_forced(self):
        """use_pallas=True routes through the kernel (interpret off-TPU) and matches."""
        q, k, v = qkv()
        ref = dot_product_attention(q, k, v, causal=True, use_pallas=False)
        out = dot_product_attention(q, k, v, causal=True, use_pallas=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_ragged_kv_length_masked(self):
        """S not a multiple of block_kv: padding columns must not leak into softmax."""
        q, k, v = qkv(B=1, T=160, N=2, K=2)  # 160 = 128 + 32
        ref = dot_product_attention(q, k, v, causal=False, use_pallas=False)
        out = flash_attention(q, k, v, causal=False, block_kv=128, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_causal_cross_length_rejected(self):
        q, _, _ = qkv(T=64)
        _, k, v = qkv(T=128)
        with pytest.raises(ValueError, match="requires T == S"):
            flash_attention(q, k, v, causal=True, interpret=True)
