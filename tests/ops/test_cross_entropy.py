"""Fused linear+CE must match the naive logits path (values AND grads)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlenlp_tpu.ops.cross_entropy import (
    causal_lm_loss,
    fused_linear_cross_entropy,
)


class TestFusedLinearCE:
    def _setup(self, B=2, T=96, H=16, V=50):
        rng = np.random.default_rng(0)
        hidden = jnp.asarray(rng.normal(size=(B, T, H)), jnp.float32)
        weight = jnp.asarray(rng.normal(size=(H, V)) * 0.1, jnp.float32)
        labels = np.asarray(rng.integers(0, V, (B, T)), np.int32)
        labels[0, -7:] = -100  # ignored tail
        return hidden, weight, jnp.asarray(labels)

    def test_matches_naive(self):
        hidden, weight, labels = self._setup()
        loss, n = fused_linear_cross_entropy(hidden, weight, labels, chunk=32)
        want = causal_lm_loss(hidden @ weight, labels)
        np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)
        assert int(n) == int((np.asarray(labels) != -100).sum())

    def test_chunk_not_dividing_T(self):
        hidden, weight, labels = self._setup(T=50)
        loss, _ = fused_linear_cross_entropy(hidden, weight, labels, chunk=16)
        want = causal_lm_loss(hidden @ weight, labels)
        np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)

    def test_grads_match_naive(self):
        hidden, weight, labels = self._setup(T=64)

        def fused(h, w):
            return fused_linear_cross_entropy(h, w, labels, chunk=16)[0]

        def naive(h, w):
            return causal_lm_loss(h @ w, labels)

        gh_f, gw_f = jax.grad(fused, argnums=(0, 1))(hidden, weight)
        gh_n, gw_n = jax.grad(naive, argnums=(0, 1))(hidden, weight)
        np.testing.assert_allclose(np.asarray(gh_f), np.asarray(gh_n), atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_n), atol=1e-5)

    def test_bf16_hidden_ok(self):
        hidden, weight, labels = self._setup()
        loss, _ = fused_linear_cross_entropy(
            hidden.astype(jnp.bfloat16), weight, labels, chunk=32
        )
        want = causal_lm_loss(
            (hidden.astype(jnp.bfloat16) @ weight.astype(jnp.bfloat16)).astype(jnp.float32),
            labels,
        )
        np.testing.assert_allclose(float(loss), float(want), rtol=2e-2)
