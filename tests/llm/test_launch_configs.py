"""The shipped launch artifacts (llm/config/<model>/*.json — the reference's
canonical launch interface) must parse and drive their entry points end-to-end.

Each test loads the SHIPPED json, overrides only model/data/output/size knobs
to tiny fixtures, and runs the real entry main on the 8-device CPU mesh —
the pretrain config keeps its tp2 x sharding4 stage2 topology (the baseline
row's layout, /root/reference/llm/docs/pretrain.rst:188)."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "llm"))
sys.path.insert(0, os.path.join(REPO, "llm", "alignment", "dpo"))

CONFIG_DIR = os.path.join(REPO, "llm", "config", "llama")

from test_entrypoints import tiny_hub  # noqa: E402,F401  (shared fixture)


def _load(name, **overrides):
    with open(os.path.join(CONFIG_DIR, name)) as f:
        cfg = json.load(f)
    cfg.update(overrides)
    return cfg


class TestShippedConfigs:
    def test_pretrain_tp2sd4_stage2(self, tiny_hub, tmp_path, monkeypatch):
        """The headline-row artifact: tp2 x sharding4 stage2 preserved on the
        8-device CPU mesh, tiny model/data substituted."""
        import run_pretrain

        cfg = _load(
            "pretrain-llama_7b-tp2sd4_stage2.json",
            model_name_or_path=str(tiny_hub["model"]),
            tokenizer_name_or_path=str(tiny_hub["model"]),
            input_dir=str(tiny_hub["corpus"]),
            output_dir=str(tmp_path / "out"),
            max_seq_length=32,
            gradient_accumulation_steps=1,
            max_steps=2,
            save_steps=2,
            eval_steps=2,
            warmup_steps=1,
            do_eval=False,
            bf16=False,
            dtype="float32",
            use_flash_attention=False,
        )
        assert cfg["tensor_parallel_degree"] == 2 and cfg["sharding_parallel_degree"] == 4
        assert cfg["sharding"] == "stage2"
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_pretrain.py", str(p)])
        trainer = run_pretrain.main()
        assert trainer.state.global_step == 2
        mesh = trainer.mesh
        assert mesh.shape.get("tp") == 2 and mesh.shape.get("fsdp") == 4

    def test_sft_argument(self, tiny_hub, tmp_path, monkeypatch):
        import run_finetune

        cfg = _load(
            "sft_argument.json",
            model_name_or_path=str(tiny_hub["model"]),
            dataset_name_or_path=str(tiny_hub["sft"]),
            output_dir=str(tmp_path / "out"),
            max_length=32,
            src_length=16,
            gradient_accumulation_steps=1,
            per_device_train_batch_size=1,
            max_steps=2,
            evaluation_strategy="no",
            save_strategy="no",
            do_eval=False,
            bf16=False,
            dtype="float32",
            use_flash_attention=False,
        )
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_finetune.py", str(p)])
        trainer = run_finetune.main()
        assert trainer.state.global_step == 2

    def test_dpo_argument(self, tiny_hub, tmp_path, monkeypatch):
        import run_dpo

        data_dir = tmp_path / "pref"
        data_dir.mkdir()
        with open(data_dir / "train.json", "w") as f:
            for _ in range(16):
                f.write(json.dumps({"src": "a b", "chosen": "c d", "rejected": "e f"}) + "\n")
        cfg = _load(
            "dpo_argument.json",
            model_name_or_path=str(tiny_hub["model"]),
            dataset_name_or_path=str(data_dir),
            output_dir=str(tmp_path / "out"),
            max_length=16,
            max_prompt_length=8,
            gradient_accumulation_steps=1,
            max_steps=2,
            evaluation_strategy="no",
            save_strategy="no",
            do_eval=False,
            bf16=False,
            dtype="float32",
            use_flash_attention=False,
            tensor_parallel_degree=2,  # tiny model has 2 heads; the 7B artifact says 8
        )
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_dpo.py", str(p)])
        trainer = run_dpo.main()
        assert trainer.state.global_step == 2
