"""The shipped launch artifacts (llm/config/<model>/*.json — the reference's
canonical launch interface) must parse and drive their entry points end-to-end.

Each test loads the SHIPPED json, overrides only model/data/output/size knobs
to tiny fixtures, and runs the real entry main on the 8-device CPU mesh —
the pretrain config keeps its tp2 x sharding4 stage2 topology (the baseline
row's layout, /root/reference/llm/docs/pretrain.rst:188)."""

import json
import numpy as np
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "llm"))
sys.path.insert(0, os.path.join(REPO, "llm", "alignment", "dpo"))

CONFIG_DIR = os.path.join(REPO, "llm", "config", "llama")

from test_entrypoints import tiny_hub  # noqa: E402,F401  (shared fixture)


def _load(name, **overrides):
    with open(os.path.join(CONFIG_DIR, name)) as f:
        cfg = json.load(f)
    cfg.update(overrides)
    return cfg


class TestShippedConfigs:
    def test_pretrain_tp2sd4_stage2(self, tiny_hub, tmp_path, monkeypatch):
        """The headline-row artifact: tp2 x sharding4 stage2 preserved on the
        8-device CPU mesh, tiny model/data substituted."""
        import run_pretrain

        cfg = _load(
            "pretrain-llama_7b-tp2sd4_stage2.json",
            model_name_or_path=str(tiny_hub["model"]),
            tokenizer_name_or_path=str(tiny_hub["model"]),
            input_dir=str(tiny_hub["corpus"]),
            output_dir=str(tmp_path / "out"),
            max_seq_length=32,
            gradient_accumulation_steps=1,
            max_steps=2,
            save_steps=2,
            eval_steps=2,
            warmup_steps=1,
            do_eval=False,
            bf16=False,
            dtype="float32",
            use_flash_attention=False,
        )
        assert cfg["tensor_parallel_degree"] == 2 and cfg["sharding_parallel_degree"] == 4
        assert cfg["sharding"] == "stage2"
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_pretrain.py", str(p)])
        trainer = run_pretrain.main()
        assert trainer.state.global_step == 2
        mesh = trainer.mesh
        assert mesh.shape.get("tp") == 2 and mesh.shape.get("fsdp") == 4

    def test_sft_argument(self, tiny_hub, tmp_path, monkeypatch):
        import run_finetune

        cfg = _load(
            "sft_argument.json",
            model_name_or_path=str(tiny_hub["model"]),
            dataset_name_or_path=str(tiny_hub["sft"]),
            output_dir=str(tmp_path / "out"),
            max_length=32,
            src_length=16,
            gradient_accumulation_steps=1,
            per_device_train_batch_size=1,
            max_steps=2,
            evaluation_strategy="no",
            save_strategy="no",
            do_eval=False,
            bf16=False,
            dtype="float32",
            use_flash_attention=False,
        )
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_finetune.py", str(p)])
        trainer = run_finetune.main()
        assert trainer.state.global_step == 2

    def test_dpo_argument(self, tiny_hub, tmp_path, monkeypatch):
        import run_dpo

        data_dir = tmp_path / "pref"
        data_dir.mkdir()
        with open(data_dir / "train.json", "w") as f:
            for _ in range(16):
                f.write(json.dumps({"src": "a b", "chosen": "c d", "rejected": "e f"}) + "\n")
        cfg = _load(
            "dpo_argument.json",
            model_name_or_path=str(tiny_hub["model"]),
            dataset_name_or_path=str(data_dir),
            output_dir=str(tmp_path / "out"),
            max_length=16,
            max_prompt_length=8,
            gradient_accumulation_steps=1,
            max_steps=2,
            evaluation_strategy="no",
            save_strategy="no",
            do_eval=False,
            bf16=False,
            dtype="float32",
            use_flash_attention=False,
            tensor_parallel_degree=2,  # tiny model has 2 heads; the 7B artifact says 8
        )
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_dpo.py", str(p)])
        trainer = run_dpo.main()
        assert trainer.state.global_step == 2


# ---------------------------------------------------------------- config zoo
ZOO_ROOT = os.path.join(REPO, "llm", "config")
ZOO_DIRS = sorted(d for d in os.listdir(ZOO_ROOT)
                  if os.path.isdir(os.path.join(ZOO_ROOT, d)))

sys.path.insert(0, os.path.join(REPO, "tests", "transformers"))
from test_modeling_common import CAUSAL_CASES  # noqa: E402

# config-zoo dir -> tiny family case (test_modeling_common registry)
ZOO_FAMILY = {
    "qwen": "qwen", "qwen2": "qwen2", "mixtral": "mixtral", "mistral": "mistral",
    "baichuan": "baichuan", "deepseek-v2": "deepseek_v2", "gpt-3": "gpt",
    "opt": "opt", "bloom": "bloom", "chatglm": "chatglm", "chatglm2": "chatglm_v2",
    "gemma": "gemma", "yuan": "yuan", "llama": "llama",
}


def _zoo_files():
    out = []
    for d in ZOO_DIRS:
        for f in sorted(os.listdir(os.path.join(ZOO_ROOT, d))):
            if f.endswith(".json"):
                out.append((d, f))
    return out


class TestConfigZoo:
    def test_every_family_has_a_config_dir(self):
        assert len(ZOO_DIRS) >= 12, ZOO_DIRS
        for d in ZOO_DIRS:
            assert d in ZOO_FAMILY, f"no tiny-family mapping for llm/config/{d}"

    @pytest.mark.parametrize("dirname,fname", _zoo_files())
    def test_config_parses_into_entry_dataclasses(self, dirname, fname):
        """Every shipped JSON must round-trip through the SAME dataclasses its
        entry point uses — unknown or mistyped keys fail here."""
        import run_finetune
        import run_pretrain
        from paddlenlp_tpu.trainer import PdArgumentParser

        path = os.path.join(ZOO_ROOT, dirname, fname)
        if "pretrain" in fname:
            parser = PdArgumentParser((run_pretrain.ModelArguments, run_pretrain.DataArguments,
                                       run_pretrain.PreTrainingArguments))
        elif "dpo" in fname:
            import run_dpo
            parser = PdArgumentParser((run_dpo.ModelArguments, run_dpo.DPOArguments,
                                       run_dpo.TrainingArguments))
        else:  # sft / lora
            parser = PdArgumentParser((run_finetune.ModelArguments, run_finetune.DataArguments,
                                       run_finetune.TrainingArguments))
        parsed = parser.parse_json_file(path)
        assert parsed[0].model_name_or_path

    @pytest.mark.parametrize("dirname", [d for d in ZOO_DIRS if d != "llama"])
    def test_sft_smoke_trains_tiny(self, dirname, tmp_path, monkeypatch):
        """The shipped sft artifact drives run_finetune end-to-end on a tiny
        checkpoint of ITS OWN family (2 steps, degrees shrunk to fit)."""
        import run_finetune
        from tokenizers import Tokenizer
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        from paddlenlp_tpu.transformers import PretrainedTokenizer

        cls, cfg_fn = CAUSAL_CASES[ZOO_FAMILY[dirname]]
        model_dir = tmp_path / "tiny"
        cfg = cfg_fn()
        cfg.eos_token_id = 2
        cfg.pad_token_id = 0
        cls.from_config(cfg, seed=0).save_pretrained(str(model_dir))
        vocab = {"<pad>": 0, "<s>": 1, "</s>": 2, "<unk>": 3}
        for i, w in enumerate("a b c d e f g h i j k l m n o p".split()):
            vocab[w] = i + 4
        t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
        t.pre_tokenizer = Whitespace()
        PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", bos_token="<s>",
                            eos_token="</s>", unk_token="<unk>").save_pretrained(str(model_dir))
        data_dir = tmp_path / "sft"
        data_dir.mkdir()
        rows = [{"src": "a b c", "tgt": "d e"}, {"src": "f g", "tgt": "h i j"}] * 16
        with open(data_dir / "train.json", "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

        with open(os.path.join(ZOO_ROOT, dirname, "sft_argument.json")) as f:
            cfg_json = json.load(f)
        cfg_json.update(
            model_name_or_path=str(model_dir),
            dataset_name_or_path=str(data_dir),
            output_dir=str(tmp_path / "out"),
            max_length=32, src_length=16,
            per_device_train_batch_size=1, gradient_accumulation_steps=1,
            max_steps=2, num_train_epochs=1,
            evaluation_strategy="no", save_strategy="no", do_eval=False,
            bf16=False, dtype="float32", use_flash_attention=False,
            tensor_parallel_degree=1, pipeline_parallel_degree=1,
            sharding_parallel_degree=1, recompute=False, zero_padding=False,
        )
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps(cfg_json))
        monkeypatch.setattr(sys, "argv", ["run_finetune.py", str(p)])
        trainer = run_finetune.main()
        assert trainer.state.global_step == 2
        losses = [h["loss"] for h in trainer.state.log_history if "loss" in h]
        assert losses and all(np.isfinite(l) for l in losses)
