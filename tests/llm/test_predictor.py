"""Predictor + server tests (reference tests/llm/test_predictor.py pattern)."""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "llm", "predict"))


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM, PretrainedTokenizer

    d = tmp_path_factory.mktemp("predict-model")
    cfg = LlamaConfig(vocab_size=32, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=128,
                      eos_token_id=2, pad_token_id=0)
    LlamaForCausalLM.from_config(cfg, seed=0).save_pretrained(str(d))
    vocab = {"<pad>": 0, "<s>": 1, "</s>": 2, "<unk>": 3}
    for i, w in enumerate("alpha beta gamma delta epsilon zeta eta theta".split()):
        vocab[w] = i + 4
    t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    t.pre_tokenizer = Whitespace()
    PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", bos_token="<s>", eos_token="</s>",
                        unk_token="<unk>").save_pretrained(str(d))
    return str(d)


class TestPredictors:
    def _args(self, model_dir, **kw):
        from predictor import PredictorArgument

        defaults = dict(model_name_or_path=model_dir, dtype="float32", max_length=8,
                        batch_size=2, decode_strategy="greedy_search", num_kv_blocks=64,
                        block_size=4, max_blocks_per_seq=16)
        defaults.update(kw)
        return PredictorArgument(**defaults)

    def test_eager_and_block_agree(self, model_dir):
        from predictor import create_predictor

        texts = ["alpha beta gamma", "delta epsilon"]
        eager = create_predictor(self._args(model_dir, mode="eager"))
        block = create_predictor(self._args(model_dir, mode="block"), model=None)
        oe = eager.predict(texts)
        ob = block.predict(texts)
        assert oe == ob, (oe, ob)

    def test_stream_predict(self, model_dir):
        from predictor import create_predictor

        block = create_predictor(self._args(model_dir))
        pieces = list(block.stream_predict("alpha beta"))
        full = block.predict(["alpha beta"])[0]
        assert "".join(pieces) == full

    def test_unknown_mode(self, model_dir):
        from predictor import create_predictor

        with pytest.raises(ValueError, match="unknown predictor mode"):
            create_predictor(self._args(model_dir, mode="static"))


class TestServer:
    def test_http_generate_and_stream(self, model_dir):
        import socket

        from flask_server import make_handler
        from http.server import ThreadingHTTPServer

        from predictor import create_predictor

        predictor = create_predictor(self._args(model_dir))
        server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(predictor, threading.Lock()))
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            # health
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=30) as r:
                assert json.loads(r.read())["status"] == "ok"
            # non-stream generate
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"src": "alpha beta"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                out = json.loads(r.read())["output"]
            assert isinstance(out, str)
            # streaming generate
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"src": "alpha beta", "stream": True}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                body = r.read().decode()
            assert "data:" in body and "[DONE]" in body
            pieces = [json.loads(line[6:])["token"] for line in body.splitlines()
                      if line.startswith("data:") and "[DONE]" not in line]
            assert "".join(pieces) == out
            # bad request
            req = urllib.request.Request(f"http://127.0.0.1:{port}/generate", data=b"not json",
                                         headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=30)
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            server.shutdown()

    _args = TestPredictors._args
