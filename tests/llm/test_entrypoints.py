"""End-to-end entry-point tests (reference tests/llm pattern: run the actual
llm/run_*.py scripts in-process against tiny fixtures)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "llm"))


@pytest.fixture(scope="module")
def tiny_hub(tmp_path_factory):
    """A hub dir with tiny llama + tokenizer + a .bin/.idx corpus + sft jsonl."""
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    from paddlenlp_tpu.data import MMapIndexedDatasetBuilder
    from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM, PretrainedTokenizer

    root = tmp_path_factory.mktemp("hub")
    model_dir = root / "tiny-llama"
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=2, pad_token_id=0)
    LlamaForCausalLM.from_config(cfg, seed=0).save_pretrained(str(model_dir))

    vocab = {"<pad>": 0, "<s>": 1, "</s>": 2, "<unk>": 3}
    for i, w in enumerate("a b c d e f g h i j k l m n o p".split()):
        vocab[w] = i + 4
    t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    t.pre_tokenizer = Whitespace()
    tok = PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", bos_token="<s>",
                              eos_token="</s>", unk_token="<unk>")
    tok.save_pretrained(str(model_dir))

    # corpus
    rng = np.random.default_rng(0)
    builder = MMapIndexedDatasetBuilder(str(root / "corpus"), dtype=np.uint16)
    for _ in range(64):
        builder.add_document(rng.integers(4, 20, size=int(rng.integers(20, 60))))
    builder.finalize()

    # sft data
    data_dir = root / "sft"
    data_dir.mkdir()
    rows = [{"src": "a b c", "tgt": "d e"}, {"src": "f g", "tgt": "h i j"}] * 32
    with open(data_dir / "train.json", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    with open(data_dir / "dev.json", "w") as f:
        for r in rows[:4]:
            f.write(json.dumps(r) + "\n")
    return {"root": root, "model": model_dir, "corpus": root / "corpus", "sft": data_dir}


class TestRunPretrain:
    def test_pretrain_from_json_config(self, tiny_hub, tmp_path, monkeypatch):
        import run_pretrain

        cfg = {
            "model_name_or_path": str(tiny_hub["model"]),
            "input_dir": str(tiny_hub["corpus"]),
            "output_dir": str(tmp_path / "out"),
            "max_seq_length": 32,
            "per_device_train_batch_size": 2,
            "max_steps": 4,
            "logging_steps": 2,
            "save_steps": 4,
            "save_strategy": "steps",
            "do_train": True,
            "learning_rate": 1e-3,
            "dtype": "float32",
        }
        cfg_path = tmp_path / "pretrain.json"
        cfg_path.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_pretrain.py", str(cfg_path)])
        trainer = run_pretrain.main()
        assert trainer.state.global_step == 4
        assert os.path.isdir(tmp_path / "out" / "checkpoint-4")
        assert os.path.isfile(tmp_path / "out" / "model.safetensors")

    def test_resume_from_checkpoint(self, tiny_hub, tmp_path, monkeypatch):
        import run_pretrain

        out = tmp_path / "out2"
        base = {
            "model_name_or_path": str(tiny_hub["model"]),
            "input_dir": str(tiny_hub["corpus"]),
            "output_dir": str(out),
            "max_seq_length": 32,
            "per_device_train_batch_size": 2,
            "max_steps": 2,
            "save_steps": 2,
            "save_strategy": "steps",
            "do_train": True,
            "dtype": "float32",
        }
        p = tmp_path / "a.json"
        p.write_text(json.dumps(base))
        monkeypatch.setattr(sys, "argv", ["run_pretrain.py", str(p)])
        run_pretrain.main()
        base["max_steps"] = 4
        p.write_text(json.dumps(base))
        monkeypatch.setattr(sys, "argv", ["run_pretrain.py", str(p)])
        trainer = run_pretrain.main()  # auto-resumes from checkpoint-2
        assert trainer.state.global_step == 4


class TestRunFinetune:
    def test_sft_zero_padding(self, tiny_hub, tmp_path, monkeypatch):
        import run_finetune

        cfg = {
            "model_name_or_path": str(tiny_hub["model"]),
            "dataset_name_or_path": str(tiny_hub["sft"]),
            "output_dir": str(tmp_path / "sft_out"),
            "max_length": 32,
            "per_device_train_batch_size": 1,
            "max_steps": 3,
            "logging_steps": 1,
            "save_strategy": "no",
            "do_train": True,
            "do_eval": True,
            "dtype": "float32",
        }
        p = tmp_path / "sft.json"
        p.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_finetune.py", str(p)])
        trainer = run_finetune.main()
        assert trainer.state.global_step == 3

    def test_sft_lora(self, tiny_hub, tmp_path, monkeypatch):
        import run_finetune

        cfg = {
            "model_name_or_path": str(tiny_hub["model"]),
            "dataset_name_or_path": str(tiny_hub["sft"]),
            "output_dir": str(tmp_path / "lora_out"),
            "max_length": 32,
            "per_device_train_batch_size": 1,
            "max_steps": 2,
            "save_strategy": "no",
            "do_train": True,
            "lora": True,
            "lora_rank": 4,
            "dtype": "float32",
        }
        p = tmp_path / "lora.json"
        p.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_finetune.py", str(p)])
        trainer = run_finetune.main()
        assert trainer.state.global_step == 2
        assert os.path.isfile(tmp_path / "lora_out" / "lora_model.safetensors")


class TestPreprocess:
    def test_preprocess_tool(self, tiny_hub, tmp_path):
        corpus = tmp_path / "raw.jsonl"
        with open(corpus, "w") as f:
            for i in range(10):
                f.write(json.dumps({"text": "a b c d e f g"}) + "\n")
        out_prefix = tmp_path / "prep" / "data"
        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "llm", "tools", "preprocess_data.py"),
             "--input", str(corpus), "--output_prefix", str(out_prefix),
             "--tokenizer_name_or_path", str(tiny_hub["model"]), "--append_eos"],
            capture_output=True, text=True,
        )
        assert rc.returncode == 0, rc.stderr[-2000:]
        from paddlenlp_tpu.data import MMapIndexedDataset

        ds = MMapIndexedDataset(str(out_prefix))
        assert ds.n_docs == 10
        assert ds[0][-1] == 2  # eos appended
