"""Training metrics plane (ISSUE 2 acceptance): a smoke training loop must
expose train_step_seconds / train_tokens_per_second through the shared
MetricsRegistry in Prometheus text format, and the opt-in HTTP exporter must
serve them.

The loop here is a *fake* one — it drives the TrainerCallback events the real
``Trainer.train()`` emits (on_train_begin → [on_step_begin → jit work →
on_step_end(step_tokens=...)] → on_log → on_train_end) without building a
device mesh, so the test runs on any jax version/backend the container has."""

import http.client
import json
import math
import time

import jax
import jax.numpy as jnp
import pytest

from paddlenlp_tpu.observability import lint_exposition, parse_prometheus_text
from paddlenlp_tpu.serving.metrics import MetricsRegistry
from paddlenlp_tpu.trainer import TrainingArguments
from paddlenlp_tpu.trainer.integrations import MetricsCallback
from paddlenlp_tpu.trainer.trainer_callback import TrainerControl, TrainerState

MAX_STEPS = 4
STEP_TOKENS = 64


class _FlopsModel:
    """Just the surface MetricsCallback reads off the model."""

    @staticmethod
    def get_model_flops(*_):
        return 6.0e6  # per-token flops of a toy model


def run_fake_training_loop(registry: MetricsRegistry, tmp_path, **arg_overrides):
    args = TrainingArguments(output_dir=str(tmp_path), report_to=[],
                             logging_steps=2, **arg_overrides)
    state, control = TrainerState(), TrainerControl()
    cb = MetricsCallback(registry=registry)
    cb.on_train_begin(args, state, control, model=_FlopsModel())
    for step in range(1, MAX_STEPS + 1):
        cb.on_step_begin(args, state, control)
        # a fresh jit closure per step: real device work + a backend compile
        # for the compile-count series, mirroring what a train step costs
        jax.jit(lambda x, _s=step: (x * _s).sum())(jnp.ones((8, 8))).block_until_ready()
        time.sleep(0.001)
        state.global_step = step
        state.epoch = step / MAX_STEPS
        cb.on_step_end(args, state, control, step_tokens=STEP_TOKENS)
        if step % args.logging_steps == 0:
            cb.on_log(args, state, control,
                      logs={"loss": 2.5, "learning_rate": 1e-3, "grad_norm": 0.7})
    cb.on_train_end(args, state, control)
    return cb


@pytest.fixture(scope="module")
def trained_registry(tmp_path_factory):
    registry = MetricsRegistry()
    run_fake_training_loop(registry, tmp_path_factory.mktemp("mcb"))
    return registry


class TestMetricsCallback:
    def test_step_series_populated(self, trained_registry):
        reg = trained_registry
        assert reg.get("train_step_seconds").count() == MAX_STEPS
        assert reg.get("train_step_seconds").sum() > 0
        assert reg.get("train_steps_total").value() == MAX_STEPS
        assert reg.get("train_tokens_total").value() == MAX_STEPS * STEP_TOKENS
        assert reg.get("train_tokens_per_second").value() > 0
        assert reg.get("train_epoch").value() == 1.0

    def test_log_series_populated(self, trained_registry):
        reg = trained_registry
        assert reg.get("train_loss").value() == 2.5
        assert reg.get("train_learning_rate").value() == 1e-3
        assert reg.get("train_grad_norm").value() == 0.7

    def test_jit_compiles_observed(self, trained_registry):
        reg = trained_registry
        assert reg.get("jax_jit_compile_total").value() >= MAX_STEPS
        assert reg.get("jax_jit_compile_seconds_total").value() > 0

    def test_prometheus_exposition_valid(self, trained_registry):
        text = trained_registry.expose()
        assert "# TYPE train_step_seconds histogram" in text
        assert "# TYPE train_tokens_per_second gauge" in text
        assert lint_exposition(text) == []
        fams = parse_prometheus_text(text)
        assert fams["train_step_seconds"].value("train_step_seconds_count") == MAX_STEPS
        assert fams["train_tokens_per_second"].value() > 0


class TestCheckpointAgeGauge:
    """ckpt_last_commit_age_seconds: the async-save health signal."""

    def test_nan_before_first_commit(self, monkeypatch):
        from paddlenlp_tpu.trainer import integrations

        monkeypatch.setattr(integrations, "_LAST_COMMIT_T", None)
        registry = MetricsRegistry()
        integrations.register_training_metrics(registry)
        gauge = registry.get("ckpt_last_commit_age_seconds")
        assert math.isnan(gauge.value())
        # NaN renders as the literal Prometheus NaN, and the exposition stays lint-clean
        text = registry.expose()
        assert "ckpt_last_commit_age_seconds NaN" in text
        assert lint_exposition(text) == []

    def test_age_tracks_last_commit(self, monkeypatch):
        from paddlenlp_tpu.trainer import integrations

        registry = MetricsRegistry()
        integrations.register_training_metrics(registry)
        monkeypatch.setattr(integrations, "_LAST_COMMIT_T", time.time() - 7.0)
        age = registry.get("ckpt_last_commit_age_seconds").value()
        assert 6.5 <= age <= 30.0
        integrations.note_checkpoint_commit(step=3)
        assert registry.get("ckpt_last_commit_age_seconds").value() < 6.5


class TestHttpExporter:
    def test_opt_in_exporter_serves_registry(self, tmp_path):
        registry = MetricsRegistry()
        registry.gauge("train_loss", "loss").set(1.5)
        cb = MetricsCallback(registry=registry)
        args = TrainingArguments(output_dir=str(tmp_path), metrics_port=0, report_to=[])
        state, control = TrainerState(), TrainerControl()
        cb.on_train_begin(args, state, control)
        try:
            assert cb.port is not None
            conn = http.client.HTTPConnection("127.0.0.1", cb.port, timeout=10)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            text = resp.read().decode()
            conn.close()
            assert resp.status == 200 and "train_loss 1.5" in text
            conn = http.client.HTTPConnection("127.0.0.1", cb.port, timeout=10)
            conn.request("GET", "/health")
            resp = conn.getresponse()
            assert resp.status == 200 and json.loads(resp.read())["status"] == "ok"
            conn.close()
        finally:
            port = cb.port
            cb.on_train_end(args, state, control)
        assert cb.port is None
        with pytest.raises(OSError):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
            conn.request("GET", "/metrics")
            conn.getresponse()

    def test_disabled_by_default(self, tmp_path):
        cb = MetricsCallback(registry=MetricsRegistry())
        args = TrainingArguments(output_dir=str(tmp_path), report_to=[])
        cb.on_train_begin(args, TrainerState(), TrainerControl())
        assert cb.port is None
        cb.on_train_end(args, TrainerState(), TrainerControl())
