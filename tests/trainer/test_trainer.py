"""Trainer end-to-end tests: loss decreases, checkpoint resume (incl. topology
change), callbacks fire, argparser parses JSON configs — mirroring the reference's
tests/trainer suite at tiny scale."""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.trainer import (
    IntervalStrategy,
    PdArgumentParser,
    Trainer,
    TrainerCallback,
    TrainingArguments,
)
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


def tiny_model(seed=0):
    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=112,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
    )
    return LlamaForCausalLM.from_config(cfg, seed=seed)


class ToyLMDataset:
    """Deterministic token sequences with a learnable pattern."""

    def __init__(self, n=64, seq_len=16, vocab=128, seed=0):
        rng = np.random.default_rng(seed)
        base = rng.integers(2, vocab, size=(8, seq_len))
        self.data = base[rng.integers(0, 8, size=n)]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        ids = self.data[i].astype(np.int32)
        return {"input_ids": ids, "labels": ids.copy()}


def make_args(tmp_path, **kw):
    defaults = dict(
        output_dir=str(tmp_path),
        per_device_train_batch_size=4,
        learning_rate=1e-3,
        max_steps=8,
        logging_steps=4,
        save_strategy="no",
        seed=0,
    )
    defaults.update(kw)
    return TrainingArguments(**defaults)


class TestTrainerLoop:
    def test_loss_decreases(self, tmp_path):
        model = tiny_model()
        args = make_args(tmp_path, max_steps=12)
        trainer = Trainer(model=model, args=args, train_dataset=ToyLMDataset())
        out = trainer.train()
        assert out.global_step == 12
        first_logs = trainer.state.log_history[0]
        assert out.training_loss < first_logs["loss"], (out.training_loss, first_logs["loss"])
        assert "train_tokens_per_second_per_device" in out.metrics

    def test_grad_accumulation_matches_big_batch(self, tmp_path):
        """accum=2 x bs=2 must match bs=4 updates (same data order)."""
        ds = ToyLMDataset(n=32)
        m1 = tiny_model()
        t1 = Trainer(model=m1, args=make_args(tmp_path / "a", max_steps=4,
                                              per_device_train_batch_size=4), train_dataset=ds)
        t1.train()
        m2 = tiny_model()
        t2 = Trainer(model=m2, args=make_args(tmp_path / "b", max_steps=4,
                                              per_device_train_batch_size=2,
                                              gradient_accumulation_steps=2), train_dataset=ds)
        t2.train()
        l1 = jax.tree.leaves(t1.train_state.params)
        l2 = jax.tree.leaves(t2.train_state.params)
        for a, b in zip(l1, l2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_evaluate(self, tmp_path):
        model = tiny_model()
        trainer = Trainer(
            model=model,
            args=make_args(tmp_path, max_steps=2),
            train_dataset=ToyLMDataset(),
            eval_dataset=ToyLMDataset(n=16, seed=3),
        )
        trainer.train()
        metrics = trainer.evaluate()
        assert "eval_loss" in metrics and np.isfinite(metrics["eval_loss"])

    def test_callbacks_fire(self, tmp_path):
        events = []

        class Recorder(TrainerCallback):
            def on_train_begin(self, args, state, control, **kw):
                events.append("train_begin")

            def on_step_end(self, args, state, control, **kw):
                events.append("step_end")

            def on_log(self, args, state, control, **kw):
                events.append("log")

            def on_train_end(self, args, state, control, **kw):
                events.append("train_end")

        trainer = Trainer(
            model=tiny_model(),
            args=make_args(tmp_path, max_steps=4, logging_steps=2),
            train_dataset=ToyLMDataset(),
            callbacks=[Recorder()],
        )
        trainer.train()
        assert events[0] == "train_begin" and events[-1] == "train_end"
        assert events.count("step_end") == 4
        assert events.count("log") == 2


class TestCheckpointResume:
    def test_save_and_resume_exact(self, tmp_path):
        """12 straight steps == 6 steps + save + resume + 6 steps (loss parity)."""
        ds = ToyLMDataset(n=64)
        m1 = tiny_model()
        t1 = Trainer(model=m1, args=make_args(tmp_path / "straight", max_steps=12), train_dataset=ds)
        t1.train()

        m2 = tiny_model()
        args2 = make_args(tmp_path / "resume", max_steps=12, save_strategy="steps", save_steps=6)
        t2 = Trainer(model=m2, args=args2, train_dataset=ds)
        t2.train()
        ckpt = os.path.join(str(tmp_path / "resume"), "checkpoint-6")
        assert os.path.isdir(ckpt)

        m3 = tiny_model(seed=99)  # different init: must be overwritten by the checkpoint
        args3 = make_args(tmp_path / "resume", max_steps=12, save_strategy="no")
        t3 = Trainer(model=m3, args=args3, train_dataset=ds)
        t3.train(resume_from_checkpoint=ckpt)
        assert t3.state.global_step == 12

        for a, b in zip(jax.tree.leaves(t1.train_state.params), jax.tree.leaves(t3.train_state.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_topology_change_resume(self, tmp_path, eight_devices):
        """Save on dp-only mesh, resume on tp=4 mesh (the reference's N1C8->N2C4
        unified-checkpoint matrix, re-expressed as mesh change)."""
        ds = ToyLMDataset(n=64)
        m1 = tiny_model()
        args1 = make_args(tmp_path / "src", max_steps=4, save_strategy="steps", save_steps=4)
        t1 = Trainer(model=m1, args=args1, train_dataset=ds)
        t1.train()
        ckpt = os.path.join(str(tmp_path / "src"), "checkpoint-4")

        m2 = tiny_model(seed=5)
        args2 = make_args(tmp_path / "dst", max_steps=8, tensor_parallel_degree=4)
        t2 = Trainer(model=m2, args=args2, train_dataset=ds)
        t2.train(resume_from_checkpoint=ckpt)
        assert t2.state.global_step == 8
        # param placement follows the new mesh
        qk = t2.train_state.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert "tp" in str(qk.sharding.spec)

    def test_rotation(self, tmp_path):
        args = make_args(tmp_path, max_steps=6, save_strategy="steps", save_steps=2, save_total_limit=2)
        t = Trainer(model=tiny_model(), args=args, train_dataset=ToyLMDataset())
        t.train()
        ckpts = sorted(d for d in os.listdir(tmp_path) if d.startswith("checkpoint-"))
        assert ckpts == ["checkpoint-4", "checkpoint-6"]


class TestShardedTraining:
    def test_fsdp_tp_loss_parity(self, tmp_path, eight_devices):
        """fsdp=2 x tp=4 training tracks dp-only training step-for-step.

        SGD keeps the comparison linear in the gradients (Adam's first-step update
        is ~lr*sign(g), which amplifies reduction-order rounding into sign flips),
        so per-step loss and grad-norm parity is tight.
        """
        import optax

        ds = ToyLMDataset(n=32)

        losses = {}
        for name, extra in {
            "ref": {},  # dp=8 -> 8 data shards
            "sharded": dict(tensor_parallel_degree=4, sharding="stage3", sharding_parallel_degree=2),
        }.items():
            model = tiny_model()
            per_step = []

            class Rec(TrainerCallback):
                def on_log(self, args, state, control, logs=None, **kw):
                    if logs and "loss" in logs:
                        per_step.append((logs["loss"], logs["grad_norm"]))

            # keep the GLOBAL batch identical (16) across topologies
            args = make_args(tmp_path / name, max_steps=4, logging_steps=1, **extra)
            args.per_device_train_batch_size = 16 // args.dataset_world_size
            t = Trainer(model=model, args=args,
                        train_dataset=ds, callbacks=[Rec()],
                        optimizers=(optax.sgd(5e-2), None))
            t.train()
            losses[name] = per_step
            if name == "sharded":
                qk = t.train_state.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
                assert "tp" in str(qk.sharding.spec) and "fsdp" in str(qk.sharding.spec)

        for (l_ref, g_ref), (l_sh, g_sh) in zip(losses["ref"], losses["sharded"]):
            np.testing.assert_allclose(l_ref, l_sh, atol=1e-4)
            np.testing.assert_allclose(g_ref, g_sh, rtol=1e-3)


class TestArgParser:
    def test_json_config_roundtrip(self, tmp_path):
        cfg = {
            "output_dir": str(tmp_path),
            "per_device_train_batch_size": 2,
            "learning_rate": 3e-4,
            "max_steps": 10,
            "tensor_parallel_degree": 4,
            "sharding": "stage2",
            "bf16": True,
        }
        path = tmp_path / "run.json"
        path.write_text(json.dumps(cfg))
        parser = PdArgumentParser([TrainingArguments])
        (args,) = parser.parse_json_file(str(path))
        assert args.learning_rate == 3e-4
        assert args.tensor_parallel_degree == 4
        assert args.sharding_stage == 2
        assert args.bf16 is True

    def test_cli_args(self, tmp_path):
        parser = PdArgumentParser([TrainingArguments])
        (args,) = parser.parse_args_into_dataclasses(
            ["--output_dir", str(tmp_path), "--learning_rate", "1e-4", "--bf16", "true",
             "--logging_strategy", "epoch"]
        )
        assert args.learning_rate == 1e-4
        assert args.bf16 is True
        assert args.logging_strategy == IntervalStrategy.EPOCH

    def test_unknown_cli_arg_raises(self, tmp_path):
        parser = PdArgumentParser([TrainingArguments])
        with pytest.raises(ValueError):
            parser.parse_args_into_dataclasses(["--output_dir", str(tmp_path), "--not_a_flag", "1"])


class TestContextParallel:
    def test_cp_training_loss_parity(self, tmp_path, eight_devices):
        """cp=2 ring-attention training tracks dp-only training per step."""
        import optax

        ds = ToyLMDataset(n=32)
        results = {}
        for name, extra in {"ref": {}, "cp": dict(context_parallel_degree=2)}.items():
            model = tiny_model()
            per_step = []

            class Rec(TrainerCallback):
                def on_log(self, args, state, control, logs=None, **kw):
                    if logs and "loss" in logs:
                        per_step.append(logs["loss"])

            args = make_args(tmp_path / f"cp_{name}", max_steps=3, logging_steps=1, **extra)
            args.per_device_train_batch_size = 16 // args.dataset_world_size
            t = Trainer(model=model, args=args, train_dataset=ds, callbacks=[Rec()],
                        optimizers=(optax.sgd(5e-2), None))
            t.train()
            results[name] = per_step
        # cp pre-shifts labels host-side; the last token of each row is dropped from
        # the loss in both cases, so losses match exactly
        np.testing.assert_allclose(results["ref"], results["cp"], atol=2e-4)

    def test_cp_eval_matches_ref(self, tmp_path, eight_devices):
        """evaluate() under cp must not double-shift labels."""
        ds = ToyLMDataset(n=16)
        ref = Trainer(model=tiny_model(), args=make_args(tmp_path / "er", max_steps=1),
                      train_dataset=ds, eval_dataset=ds)
        m_ref = ref.evaluate()
        cp = Trainer(model=tiny_model(), args=make_args(tmp_path / "ec", max_steps=1,
                                                        context_parallel_degree=2),
                     train_dataset=ds, eval_dataset=ds)
        m_cp = cp.evaluate()
        # cp pre-shift drops the final token from the loss; recompute ref the same way
        np.testing.assert_allclose(m_ref["eval_loss"], m_cp["eval_loss"], atol=5e-3)

    def test_cp_with_attention_mask_positions_correct(self, tmp_path, eight_devices):
        """cp fallback path (attention_mask present) must mask by absolute position."""

        class MaskedDS(ToyLMDataset):
            def __getitem__(self, i):
                out = super().__getitem__(i)
                out["attention_mask"] = np.ones_like(out["input_ids"])
                return out

        ds = MaskedDS(n=16)
        results = {}
        for name, extra in {"ref": {}, "cp": dict(context_parallel_degree=2)}.items():
            per_step = []

            class Rec(TrainerCallback):
                def on_log(self, args, state, control, logs=None, **kw):
                    if logs and "loss" in logs:
                        per_step.append(logs["loss"])

            args = make_args(tmp_path / f"m_{name}", max_steps=2, logging_steps=1, **extra)
            args.per_device_train_batch_size = 16 // args.dataset_world_size
            import optax
            t = Trainer(model=tiny_model(), args=args, train_dataset=ds, callbacks=[Rec()],
                        optimizers=(optax.sgd(5e-2), None))
            t.train()
            results[name] = per_step
        np.testing.assert_allclose(results["ref"], results["cp"], atol=2e-4)


class TestIntegrations:
    def test_jsonl_report_to(self, tmp_path):
        args = make_args(tmp_path, max_steps=4, logging_steps=2)
        args.report_to = ["jsonl"]
        t = Trainer(model=tiny_model(), args=args, train_dataset=ToyLMDataset())
        t.train()
        path = os.path.join(str(tmp_path), "metrics.jsonl")
        assert os.path.isfile(path)
        rows = [json.loads(l) for l in open(path)]
        assert len(rows) == 2 and all("loss" in r and "step" in r for r in rows)

    def test_wandb_absent_is_graceful(self, tmp_path):
        """report_to=wandb without the package must warn once and train fine."""
        args = make_args(tmp_path, max_steps=2, logging_steps=1)
        args.report_to = ["wandb"]
        t = Trainer(model=tiny_model(), args=args, train_dataset=ToyLMDataset())
        out = t.train()
        assert np.isfinite(out.training_loss)

    def test_profiler_options_writes_trace(self, tmp_path):
        """--profiler_options drives jax.profiler over the step window
        (reference utils/profiler.py add_profiler_step)."""
        trace_dir = str(tmp_path / "trace")
        args = make_args(tmp_path, max_steps=4)
        args.profiler_options = f"batch_range=[1,3];profile_path={trace_dir}"
        t = Trainer(model=tiny_model(), args=args, train_dataset=ToyLMDataset())
        t.train()
        # jax writes <dir>/plugins/profile/<ts>/*.xplane.pb
        hits = []
        for root, _, files in os.walk(trace_dir):
            hits += [f for f in files if f.endswith(".xplane.pb")]
        assert hits, f"no xplane trace under {trace_dir}"

    def test_profiler_window_open_at_train_end_still_flushes(self, tmp_path):
        """Training ending inside the batch_range window must still stop the
        trace and write the xplane (and not wedge the process profiler)."""
        trace_dir = str(tmp_path / "trace2")
        args = make_args(tmp_path, max_steps=2)
        args.profiler_options = f"batch_range=[1,10];profile_path={trace_dir}"
        t = Trainer(model=tiny_model(), args=args, train_dataset=ToyLMDataset())
        t.train()
        hits = []
        for root, _, files in os.walk(trace_dir):
            hits += [f for f in files if f.endswith(".xplane.pb")]
        assert hits, f"no xplane trace under {trace_dir}"

    def test_profiler_options_parse_errors(self):
        from paddlenlp_tpu.utils.profiler import ProfilerOptions

        with pytest.raises(ValueError, match="key=value"):
            ProfilerOptions.parse("batch_range")
        with pytest.raises(ValueError, match="batch_range"):
            ProfilerOptions.parse("batch_range=[5,2]")
        opts = ProfilerOptions.parse("batch_range=[1, 3];profile_path=/x/y")
        assert opts.batch_range == (1, 3) and opts.profile_path == "/x/y"
