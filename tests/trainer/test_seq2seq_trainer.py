"""Seq2SeqTrainer end-to-end: teacher-forced T5 finetune (loss decreases) and
generation-based eval via compute_metrics — the reference's
tests/trainer/test_seq2seq_trainer pattern at tiny scale."""

import numpy as np

import jax.numpy as jnp

from paddlenlp_tpu.trainer import Seq2SeqTrainer, TrainingArguments
from paddlenlp_tpu.transformers import T5Config, T5ForConditionalGeneration


def tiny_t5(seed=0):
    cfg = T5Config(vocab_size=64, d_model=48, d_kv=12, d_ff=96, num_layers=2,
                   num_heads=4, dropout_rate=0.0)
    return T5ForConditionalGeneration.from_config(cfg, seed=seed)


class ToySeq2SeqDataset:
    """Copy task: target = source tokens (learnable at tiny scale)."""

    def __init__(self, n=48, src_len=8, tgt_len=8, vocab=64, seed=0):
        rng = np.random.default_rng(seed)
        base = rng.integers(3, vocab, size=(6, src_len))
        self.src = base[rng.integers(0, 6, size=n)]

    def __len__(self):
        return len(self.src)

    def __getitem__(self, i):
        src = self.src[i].astype(np.int32)
        return {"input_ids": src, "labels": src.copy()}


def test_seq2seq_finetune_loss_decreases(tmp_path):
    model = tiny_t5()
    args = TrainingArguments(output_dir=str(tmp_path), per_device_train_batch_size=4,
                             learning_rate=3e-3, max_steps=12, logging_steps=4,
                             save_strategy="no", seed=0)
    trainer = Seq2SeqTrainer(model=model, args=args, train_dataset=ToySeq2SeqDataset(),
                             predict_with_generate=False)
    out = trainer.train()
    first = trainer.state.log_history[0]["loss"]
    assert out.training_loss < first, (out.training_loss, first)


def test_seq2seq_generate_eval(tmp_path):
    model = tiny_t5()
    args = TrainingArguments(output_dir=str(tmp_path), per_device_train_batch_size=4,
                             per_device_eval_batch_size=4, max_steps=2, save_strategy="no", seed=0)

    def exact_match(pred):
        preds = np.asarray(pred.predictions)
        labels = np.asarray(pred.label_ids)
        n = min(preds.shape[-1], labels.shape[-1])
        return {"exact": float((preds[:, :n] == labels[:, :n]).all(-1).mean())}

    trainer = Seq2SeqTrainer(model=model, args=args, train_dataset=ToySeq2SeqDataset(),
                             eval_dataset=ToySeq2SeqDataset(n=8),
                             compute_metrics=exact_match,
                             gen_kwargs={"max_new_tokens": 8, "do_sample": False})
    metrics = trainer.evaluate()
    assert "eval_exact" in metrics
    assert 0.0 <= metrics["eval_exact"] <= 1.0
