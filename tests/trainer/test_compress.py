"""Compression trainer: PTQ export (plain + GPTQ-calibrated) and ffn width
pruning; plus TrainingArguments config-string knob handling and
skip_data_intervals."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.trainer import Trainer, TrainingArguments
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


def tiny(scan=True):
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64,
                      use_scan_layers=scan)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def dataset(n=64):
    rows = [np.random.default_rng(0).integers(0, 64, 12).astype(np.int32) for _ in range(n)]

    class DS:
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {"input_ids": rows[i], "labels": rows[i].copy()}

    return DS()


class TestCompress:
    def test_ptq_export(self, tmp_path):
        trainer = Trainer(model=tiny(), args=TrainingArguments(output_dir=str(tmp_path)),
                          train_dataset=dataset())
        out = trainer.compress(strategy="ptq", bits=8)
        assert os.path.exists(os.path.join(out, "model_quant.safetensors"))
        assert os.path.exists(os.path.join(out, "model.safetensors"))

    def test_ptq_gptq_calibrated(self, tmp_path):
        trainer = Trainer(model=tiny(scan=False), args=TrainingArguments(output_dir=str(tmp_path)),
                          train_dataset=dataset())
        out = trainer.compress(strategy="ptq", bits=8, use_gptq=True, n_calib_batches=2,
                               match=lambda p: "mlp" in p)
        assert os.path.exists(os.path.join(out, "model_quant.safetensors"))

    def test_width_prune(self, tmp_path):
        model = tiny()
        trainer = Trainer(model=model, args=TrainingArguments(output_dir=str(tmp_path)),
                          train_dataset=dataset())
        out = trainer.compress(strategy="prune", width_mult=0.5)
        reloaded = LlamaForCausalLM.from_pretrained(out)
        assert reloaded.config.intermediate_size == 32
        logits = reloaded(input_ids=jnp.asarray([[5, 6, 7]], jnp.int32)).logits
        assert np.isfinite(np.asarray(logits)).all()

    def test_width_prune_bert(self, tmp_path):
        """dynabert's actual target archs (bert/ernie encoders) must prune too
        (round-2 weak item: compression was llama-family-only)."""
        from paddlenlp_tpu.transformers import BertConfig, BertForSequenceClassification

        cfg = BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                         num_attention_heads=2, intermediate_size=64,
                         max_position_embeddings=64, num_labels=2)
        model = BertForSequenceClassification.from_config(cfg, seed=0)
        trainer = Trainer(model=model, args=TrainingArguments(output_dir=str(tmp_path)),
                          train_dataset=dataset())
        out = trainer.compress(strategy="prune", width_mult=0.5)
        reloaded = BertForSequenceClassification.from_pretrained(out)
        assert reloaded.config.intermediate_size == 32
        logits = reloaded(input_ids=jnp.asarray([[5, 6, 7]], jnp.int32)).logits
        assert np.isfinite(np.asarray(logits)).all()


class TestDepthPrune:
    """Depth pruning must see per-layer params in BOTH naming families:
    scanned llama ("model/layers/...") and underscore-joined bert encoders
    ("bert/encoder_layer_0/...") — \\blayers?_ never matched the latter, so
    BERT depth pruning raised "no per-layer params found"."""

    @staticmethod
    def _shim(model):
        # _prune_depth only reads trainer.model / trainer.train_state, so a
        # shim keeps the test off Trainer (whose mesh setup needs a newer jax)
        import types

        return types.SimpleNamespace(model=model, train_state=None)

    def test_depth_prune_bert(self, tmp_path):
        from paddlenlp_tpu.trainer.trainer_compress import _prune_depth
        from paddlenlp_tpu.transformers import BertConfig, BertForSequenceClassification

        cfg = BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=4,
                         num_attention_heads=2, intermediate_size=64,
                         max_position_embeddings=64, num_labels=2)
        model = BertForSequenceClassification.from_config(cfg, seed=0)
        out = _prune_depth(self._shim(model), str(tmp_path / "pruned"), depth_mult=0.5)
        reloaded = BertForSequenceClassification.from_pretrained(out)
        assert reloaded.config.num_hidden_layers == 2
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params

        paths = set(flatten_params(reloaded.params))
        # kept layers are renumbered contiguously from 0
        assert any("encoder_layer_0/" in p for p in paths)
        assert any("encoder_layer_1/" in p for p in paths)
        assert not any("encoder_layer_2/" in p or "encoder_layer_3/" in p for p in paths)
        logits = reloaded(input_ids=jnp.asarray([[5, 6, 7]], jnp.int32)).logits
        assert np.isfinite(np.asarray(logits)).all()

    def test_depth_prune_llama_scanned_still_works(self, tmp_path):
        from paddlenlp_tpu.trainer.trainer_compress import _prune_depth
        from paddlenlp_tpu.transformers import LlamaForCausalLM

        model = tiny()
        out = _prune_depth(self._shim(model), str(tmp_path / "pruned"), depth_mult=0.5)
        reloaded = LlamaForCausalLM.from_pretrained(out)
        assert reloaded.config.num_hidden_layers == 1
        logits = reloaded(input_ids=jnp.asarray([[5, 6, 7]], jnp.int32)).logits
        assert np.isfinite(np.asarray(logits)).all()


class TestArgKnobs:
    def test_obsolete_fleet_options_warn(self, tmp_path):
        args = TrainingArguments(output_dir=str(tmp_path),
                                 tensor_parallel_config="enable_mp_async_allreduce",
                                 pipeline_parallel_config="enable_release_grads enable_timer",
                                 hybrid_parallel_topo_order="pp_first")
        assert args.tensor_parallel_config  # accepted, not dropped

    def test_unknown_option_raises(self, tmp_path):
        with pytest.raises(ValueError, match="unsupported option"):
            TrainingArguments(output_dir=str(tmp_path),
                              sharding_parallel_config="definitely_not_a_thing")

    def test_bad_topo_order_raises(self, tmp_path):
        with pytest.raises(ValueError, match="hybrid_parallel_topo_order"):
            TrainingArguments(output_dir=str(tmp_path), hybrid_parallel_topo_order="mp_first")

    def test_skip_data_intervals(self, tmp_path):
        args = TrainingArguments(output_dir=str(tmp_path), max_steps=3,
                                 per_device_train_batch_size=2, logging_steps=1,
                                 save_strategy="no", skip_data_intervals=[[1, 2]])
        trainer = Trainer(model=tiny(), args=args, train_dataset=dataset())
        out = trainer.train()
        # data steps 1-2 skipped untrained but consumed
        assert out.global_step == 3
        assert trainer.state.consumed_samples == 5 * args.global_train_batch_size
