"""Prompt package: template rendering, verbalizer scoring, PromptTrainer
learning, soft-prompt causal tuning with frozen base."""

import jax.numpy as jnp
import numpy as np

from paddlenlp_tpu.transformers import (
    BertConfig,
    BertForMaskedLM,
    LlamaConfig,
    LlamaForCausalLM,
)


class _TinyTok:
    """Word-level tokenizer stub with a mask token."""

    mask_token = "[MASK]"
    mask_token_id = 1

    def __init__(self):
        self.vocab = {"[PAD]": 0, "[MASK]": 1, "good": 2, "bad": 3, "movie": 4,
                      "it": 5, "was": 6, "the": 7, "great": 8, "awful": 9}

    def __call__(self, text, max_length=64, truncation=True, add_special_tokens=True):
        ids = [self.vocab.get(w, 0) for w in text.split()][:max_length]
        return {"input_ids": ids, "attention_mask": [1] * len(ids)}


class TestTemplateVerbalizer:
    def test_template_renders_mask(self):
        from paddlenlp_tpu.prompt import ManualTemplate

        tok = _TinyTok()
        t = ManualTemplate("{'text': 'text_a'} it was {'mask'}", tok)
        out = t({"text_a": "good movie", "label": 1})
        assert out["input_ids"][out["mask_position"]] == tok.mask_token_id
        assert out["label"] == 1

    def test_verbalizer_scores(self):
        from paddlenlp_tpu.prompt import ManualVerbalizer

        tok = _TinyTok()
        v = ManualVerbalizer({0: ["bad", "awful"], 1: ["good", "great"]}, tok)
        logits = jnp.zeros((1, 10)).at[0, 2].set(5.0).at[0, 8].set(3.0)  # good/great high
        scores = v.process_logits(logits)
        assert scores.shape == (1, 2)
        assert float(scores[0, 1]) > float(scores[0, 0])


class TestPromptTrainer:
    def test_learns_classification(self, tmp_path):
        from paddlenlp_tpu.prompt import ManualTemplate, ManualVerbalizer, PromptModelForClassification, PromptTrainer
        from paddlenlp_tpu.trainer import TrainingArguments

        tok = _TinyTok()
        cfg = BertConfig(vocab_size=16, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                         num_attention_heads=2, max_position_embeddings=32)
        mlm = BertForMaskedLM.from_config(cfg, seed=0)
        template = ManualTemplate("{'text': 'text_a'} it was {'mask'}", tok)
        verbalizer = ManualVerbalizer({0: "bad", 1: "good"}, tok)
        pm = PromptModelForClassification(mlm, template, verbalizer)

        rows = []
        for i in range(64):
            label = i % 2
            text = "good movie" if label else "bad movie"
            ex = template({"text_a": text})
            rows.append({"input_ids": np.asarray(ex["input_ids"], np.int32),
                         "attention_mask": np.asarray(ex["attention_mask"], np.int32),
                         "mask_position": np.asarray(ex["mask_position"], np.int32),
                         "labels": np.asarray(label, np.int32)})

        class DS:
            def __len__(self):
                return len(rows)

            def __getitem__(self, i):
                return rows[i]

        args = TrainingArguments(output_dir=str(tmp_path), max_steps=40, per_device_train_batch_size=4,
                                 learning_rate=1e-2, logging_steps=1, save_strategy="no")
        trainer = PromptTrainer(model=pm, args=args, train_dataset=DS())
        trainer.train()
        losses = [h["loss"] for h in trainer.state.log_history if "loss" in h]
        assert losses[-1] < 0.4 < losses[0], losses


class TestSoftPrompt:
    def test_soft_prompt_trains_frozen_base(self, tmp_path):
        from paddlenlp_tpu.prompt import SoftPromptModelForCausalLM
        from paddlenlp_tpu.trainer import Trainer, TrainingArguments
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params

        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64)
        base = LlamaForCausalLM.from_config(cfg, seed=0)
        sp = SoftPromptModelForCausalLM(base, n_prompt_tokens=4)
        rows = [np.random.default_rng(2).integers(0, 64, 12).astype(np.int32) for _ in range(64)]

        class DS:
            def __len__(self):
                return 64

            def __getitem__(self, i):
                return {"input_ids": rows[i], "labels": rows[i].copy()}

        before = {k: np.asarray(v).copy() for k, v in flatten_params(sp.params).items()}
        args = TrainingArguments(output_dir=str(tmp_path), max_steps=6, per_device_train_batch_size=4,
                                 learning_rate=5e-2, logging_steps=1, save_strategy="no")
        trainer = Trainer(model=sp, args=args, train_dataset=DS())
        trainer.train()
        losses = [h["loss"] for h in trainer.state.log_history if "loss" in h]
        assert losses[-1] < losses[0], losses
        after = flatten_params(trainer.train_state.params)
        # base frozen; prompt moved
        np.testing.assert_array_equal(np.asarray(before["model/norm/scale"]),
                                      np.asarray(after["model/norm/scale"]))
        assert not np.allclose(np.asarray(before["soft_prompt"]), np.asarray(after["soft_prompt"]))
        # save/load roundtrip
        sp.params = trainer.train_state.params
        sp.save_pretrained(str(tmp_path / "sp"))
        sp2 = SoftPromptModelForCausalLM.from_pretrained(
            LlamaForCausalLM.from_config(cfg, seed=0), str(tmp_path / "sp"), n_prompt_tokens=4)
        np.testing.assert_array_equal(np.asarray(sp2.params["soft_prompt"]),
                                      np.asarray(after["soft_prompt"]))
