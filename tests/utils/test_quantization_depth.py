"""Quantization depth: NF4 roundtrip fidelity, GPTQ beats round-to-nearest,
calibration-driven apply_gptq on a real (unrolled) model, QLoRA composition."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


class TestNF4:
    def test_roundtrip_error_small(self):
        from paddlenlp_tpu.quantization import nf4_dequantize, nf4_quantize

        rng = np.random.default_rng(0)
        w = rng.normal(0, 0.05, (96, 64)).astype(np.float32)
        state = nf4_quantize(w, block_size=64, double_quant=True)
        deq = np.asarray(nf4_dequantize(state, dtype=jnp.float32))
        err = np.abs(deq - w).mean() / np.abs(w).mean()
        assert err < 0.12, err  # nf4 typical relative error ~0.07
        # double quant compresses scales ~4x
        assert state["absmax_q"].dtype == np.int8

    def test_nondouble_matches_shape(self):
        from paddlenlp_tpu.quantization import nf4_dequantize, nf4_quantize

        w = np.random.default_rng(1).normal(size=(33, 7)).astype(np.float32)  # ragged
        deq = np.asarray(nf4_dequantize(nf4_quantize(w, double_quant=False), jnp.float32))
        assert deq.shape == w.shape


class TestGPTQ:
    def test_beats_rtn_on_correlated_inputs(self):
        from paddlenlp_tpu.quantization import gptq_quantize

        rng = np.random.default_rng(0)
        n_in, n_out, n_samples = 64, 32, 512
        # correlated calibration inputs (the case GPTQ exists for)
        base = rng.normal(size=(n_samples, 8))
        mix = rng.normal(size=(8, n_in))
        X = base @ mix + 0.1 * rng.normal(size=(n_samples, n_in))
        W = rng.normal(size=(n_in, n_out)).astype(np.float32)
        H = (X.T @ X).astype(np.float32)

        wq, _ = gptq_quantize(W, H, bits=4)
        qmax = 7
        s = np.abs(W).max(axis=0) / qmax
        rtn = np.clip(np.round(W / s), -8, 7) * s

        err_gptq = np.linalg.norm(X @ wq - X @ W)
        err_rtn = np.linalg.norm(X @ rtn - X @ W)
        assert err_gptq < err_rtn * 0.9, (err_gptq, err_rtn)

    def test_apply_gptq_on_model(self):
        from paddlenlp_tpu.quantization import apply_gptq
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64,
                          use_scan_layers=False)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        rng = np.random.default_rng(0)
        batches = [{"input_ids": jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)} for _ in range(2)]
        ids = batches[0]["input_ids"]
        ref = model(input_ids=ids).logits
        new_params = apply_gptq(model, batches, bits=8, match=lambda p: "mlp" in p)
        out = model.module.apply({"params": new_params}, input_ids=ids, deterministic=True).logits
        # int8 GPTQ on the mlp only: outputs close but not identical
        diff = np.abs(np.asarray(out) - np.asarray(ref)).max()
        assert 0 < diff < 0.5, diff

    def test_scan_layout_matches_unrolled(self):
        """apply_gptq on a scan-stacked model must produce the same rewritten
        weights as the unrolled layout (layouts share checkpoints; calibration
        rides the unrolled_twin)."""
        from paddlenlp_tpu.quantization import apply_gptq
        from paddlenlp_tpu.quantization.quantization_utils import unrolled_twin
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params

        kw = dict(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                  num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64)
        scan_model = LlamaForCausalLM.from_config(LlamaConfig(use_scan_layers=True, **kw), seed=0)
        rng = np.random.default_rng(0)
        batches = [{"input_ids": jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)} for _ in range(2)]
        new_stacked = apply_gptq(scan_model, batches, bits=8, match=lambda p: "mlp" in p)

        unrolled = unrolled_twin(scan_model)
        new_unrolled = apply_gptq(unrolled, batches, bits=8, match=lambda p: "mlp" in p)
        flat_s = flatten_params(new_stacked)
        flat_u = flatten_params(new_unrolled)
        for i in (0, 1):
            np.testing.assert_allclose(
                np.asarray(flat_s["model/layers/mlp/gate_proj/kernel"][i]),
                np.asarray(flat_u[f"model/layers_{i}/mlp/gate_proj/kernel"]),
                atol=1e-6,
            )
        # the rewrite changed the weights (gptq actually ran)
        orig = flatten_params(scan_model.params)["model/layers/mlp/gate_proj/kernel"]
        assert np.abs(np.asarray(flat_s["model/layers/mlp/gate_proj/kernel"]) - np.asarray(orig)).max() > 0


class TestQLoRAComposition:
    def test_lora_over_nf4_base_trains(self, tmp_path):
        """QLoRA = LoRA adapters over an nf4-requantized base (facade compose)."""
        from paddlenlp_tpu.peft import LoRAConfig, LoRAModel
        from paddlenlp_tpu.quantization import nf4_dequantize, nf4_quantize
        from paddlenlp_tpu.trainer import Trainer, TrainingArguments
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params, unflatten_params

        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        # nf4 roundtrip the attention kernels (storage compression happens offline)
        flat = dict(flatten_params(model.params))
        for p, v in list(flat.items()):
            if "self_attn" in p and p.endswith("/kernel"):
                flat[p] = nf4_dequantize(nf4_quantize(np.asarray(v)), jnp.float32)
        model.params = unflatten_params(flat)
        lora = LoRAModel(model, LoRAConfig(r=4))
        rows = [np.random.default_rng(3).integers(0, 64, 12).astype(np.int32) for _ in range(64)]

        class DS:
            def __len__(self):
                return 64

            def __getitem__(self, i):
                return {"input_ids": rows[i], "labels": rows[i].copy()}

        args = TrainingArguments(output_dir=str(tmp_path), max_steps=4, per_device_train_batch_size=4,
                                 learning_rate=1e-2, logging_steps=1, save_strategy="no")
        trainer = Trainer(model=lora, args=args, train_dataset=DS())
        trainer.train()
        losses = [h["loss"] for h in trainer.state.log_history if "loss" in h]
        assert losses[-1] < losses[0], losses


class TestA8W8:
    def _model(self):
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                          num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=64, use_scan_layers=False)
        return LlamaForCausalLM.from_config(cfg, seed=0)

    def test_int8_linear_matches_fp(self):
        from paddlenlp_tpu.quantization import int8_linear
        from paddlenlp_tpu.quantization.quantization_utils import _quantize_array

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 32)), jnp.float32)
        w = rng.normal(size=(32, 48)).astype(np.float32) * 0.2
        q, s = _quantize_array(w, 8)
        y = int8_linear(x, jnp.asarray(q), jnp.asarray(s), out_dtype=jnp.float32)
        ref = np.asarray(x) @ w
        cos = float(np.sum(np.asarray(y) * ref) /
                    (np.linalg.norm(y) * np.linalg.norm(ref) + 1e-9))
        assert cos > 0.999, cos

    def test_quantized_model_a8w8_quality(self):
        """a8w8 forward must track the fp model (top-1 agreement on most
        positions of a fixed input)."""
        from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel

        model = self._model()
        ids = jnp.asarray(np.arange(16)[None] % 90 + 3, jnp.int32)
        ref = np.asarray(model(input_ids=ids).logits[0])
        qm = QuantizedModel(model, QuantizationConfig(weight_quantize_algo="a8w8"))
        got = np.asarray(qm(input_ids=ids).logits[0])
        agree = (ref.argmax(-1) == got.argmax(-1)).mean()
        assert agree >= 0.8, agree
        # and the logits correlate strongly
        cos = float((ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-9))
        assert cos > 0.98, cos

    def test_a8w8_scan_layout_quality(self):
        """a8w8 under the DEFAULT stacked layout (nn.scan slices qweight/scales
        into the intercepted Dense): outputs must track the fp model, and match
        the unrolled a8w8 path."""
        from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                          num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=64, use_scan_layers=True)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        ids = jnp.asarray(np.arange(16)[None] % 90 + 3, jnp.int32)
        ref = np.asarray(model(input_ids=ids).logits[0])
        qm = QuantizedModel(model, QuantizationConfig(weight_quantize_algo="a8w8"))
        got = np.asarray(qm(input_ids=ids).logits[0])
        agree = (ref.argmax(-1) == got.argmax(-1)).mean()
        assert agree >= 0.8, agree
        cos = float((ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-9))
        assert cos > 0.98, cos

    def test_a8w8_calibrated_scales_fold_into_scan(self):
        """collect_act_scales on a scan model (via unrolled_twin) + fold into
        stacked act_scale leaves -> static-scale a8w8 stays close to fp."""
        from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel, collect_act_scales
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params

        cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                          num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=64, use_scan_layers=True)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        batches = [{"input_ids": jnp.asarray(np.arange(12)[None] % 90 + 3, jnp.int32)}]
        scales = collect_act_scales(model, batches)
        assert scales and all(v > 0 for v in scales.values())
        assert any("layers_0" in k for k in scales)  # observed per layer via the twin
        qm = QuantizedModel(model, QuantizationConfig(weight_quantize_algo="a8w8"),
                            act_scales=scales)
        folded = flatten_params(qm.params)
        stacked_scales = [v for p, v in folded.items() if p.endswith("/act_scale")]
        assert stacked_scales and all(v.shape == (2,) for v in stacked_scales)
        ids = batches[0]["input_ids"]
        ref = np.asarray(model(input_ids=ids).logits[0])
        got = np.asarray(qm(input_ids=ids).logits[0])
        cos = float((ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-9))
        assert cos > 0.97, cos

    def test_compress_a8w8_flow(self, tmp_path):
        """Trainer.compress(strategy='a8w8') calibrates, exports, and the
        static-scale model stays close to fp."""
        import json
        import os

        from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel
        from paddlenlp_tpu.trainer import Trainer, TrainingArguments

        model = self._model()
        data = [{"input_ids": np.asarray([3, 4, 5, 6, 7, 8], np.int32),
                 "labels": np.asarray([4, 5, 6, 7, 8, 9], np.int32)} for _ in range(4)]
        args = TrainingArguments(output_dir=str(tmp_path), per_device_train_batch_size=2)
        trainer = Trainer(model=model, args=args, train_dataset=data)
        out = trainer.compress(strategy="a8w8", n_calib_batches=2)
        assert os.path.exists(os.path.join(out, "act_scales.json"))
        assert os.path.exists(os.path.join(out, "model_quant.safetensors"))
        scales = json.load(open(os.path.join(out, "act_scales.json")))
        assert scales and all(v > 0 for v in scales.values())
        ids = jnp.asarray(np.arange(12)[None] % 90 + 3, jnp.int32)
        ref = np.asarray(model(input_ids=ids).logits[0])
        qm = QuantizedModel(model, QuantizationConfig(weight_quantize_algo="a8w8"),
                            act_scales=scales)
        got = np.asarray(qm(input_ids=ids).logits[0])
        cos = float((ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-9))
        assert cos > 0.97, cos


class TestFP8:
    """weight_quantize_algo=fp8: float8_e4m3fn weights + per-channel scales
    (XLA-native twin of the reference csrc/gpu/fp8_gemm_with_cutlass path)."""

    def _model(self):
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=96, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64,
                          use_scan_layers=False)
        return LlamaForCausalLM.from_config(cfg, seed=0)

    def test_fp8_leaf_roundtrip(self):
        from paddlenlp_tpu.quantization.quantization_utils import (
            _quantize_array_fp8, dequantize_leaf)

        w = np.random.default_rng(0).normal(0, 0.05, (64, 32)).astype(np.float32)
        q, scales = _quantize_array_fp8(w)
        assert q.dtype == jnp.float8_e4m3fn and scales.shape == (32,)
        deq = np.asarray(dequantize_leaf(jnp.asarray(q), jnp.asarray(scales), bits=8,
                                         dtype=jnp.float32))
        rel = np.abs(deq - w).mean() / np.abs(w).mean()
        assert rel < 0.04, rel  # e4m3 has ~2 mantissa-bit relative error ~1.5-3%

    def test_fp8_model_quality(self):
        from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel

        model = self._model()
        ids = jnp.asarray(np.arange(12)[None] % 90 + 3, jnp.int32)
        ref = np.asarray(model(input_ids=ids).logits[0])
        qm = QuantizedModel(model, QuantizationConfig(weight_quantize_algo="fp8"))
        got = np.asarray(qm(input_ids=ids).logits[0])
        cos = float((ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-9))
        assert cos > 0.995, cos
        # fp8 should sit between bf16 and int4 fidelity: tighter than wint4
        qm4 = QuantizedModel(model, QuantizationConfig(weight_quantize_algo="wint4"))
        got4 = np.asarray(qm4(input_ids=ids).logits[0])
        cos4 = float((ref * got4).sum() / (np.linalg.norm(ref) * np.linalg.norm(got4) + 1e-9))
        assert cos >= cos4, (cos, cos4)

    def test_fp8_scan_layout(self):
        """Stacked [L, in, out] kernels quantize with per-layer per-channel scales."""
        from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=96, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64,
                          use_scan_layers=True)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        ids = jnp.asarray(np.arange(12)[None] % 90 + 3, jnp.int32)
        ref = np.asarray(model(input_ids=ids).logits[0])
        qm = QuantizedModel(model, QuantizationConfig(weight_quantize_algo="fp8"))
        got = np.asarray(qm(input_ids=ids).logits[0])
        cos = float((ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-9))
        assert cos > 0.995, cos


class TestCompressionDepth:
    """Round-5 compression-trainer additions: QAT (STE fake-quant finetune),
    embedding quantization, depth pruning (reference trainer_compress.py)."""

    def _trainer(self, scan=False, n=6):
        from paddlenlp_tpu.trainer import Trainer, TrainingArguments
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=96, hidden_size=32, intermediate_size=64,
                          num_hidden_layers=4, num_attention_heads=2, num_key_value_heads=2,
                          max_position_embeddings=64, use_scan_layers=scan)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        data = [{"input_ids": np.asarray([3, 4, 5, 6, 7, 8], np.int32),
                 "labels": np.asarray([4, 5, 6, 7, 8, 9], np.int32)} for _ in range(n)]
        import tempfile

        args = TrainingArguments(output_dir=tempfile.mkdtemp(), per_device_train_batch_size=1)
        return Trainer(model=model, args=args, train_dataset=data)

    def test_qat_improves_quantized_loss(self, tmp_path):
        """A few STE steps must not diverge, and the export loads as wint8."""
        import os

        trainer = self._trainer()
        out = trainer.compress(strategy="qat", output_dir=str(tmp_path), n_qat_steps=8,
                               learning_rate=1e-4)
        assert os.path.exists(os.path.join(out, "model_quant.safetensors"))
        assert os.path.exists(os.path.join(out, "model.safetensors"))

    def test_embedding_quant_roundtrip(self, tmp_path):
        import os

        from paddlenlp_tpu.trainer.trainer_compress import dequantize_embedding
        from paddlenlp_tpu.utils.safetensors_io import load_file

        trainer = self._trainer()
        out = trainer.compress(strategy="embedding_quant", output_dir=str(tmp_path))
        tensors = load_file(os.path.join(out, "model_quant.safetensors"))
        qkeys = [k for k in tensors if k.endswith("qembedding")]
        assert qkeys, list(tensors)[:10]
        k = qkeys[0]
        scales = tensors[k.rsplit("/", 1)[0] + "/embed_scales"]
        deq = np.asarray(dequantize_embedding(jnp.asarray(tensors[k]), jnp.asarray(scales)))
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params

        orig = np.asarray([v for p, v in flatten_params(trainer.model.params).items()
                           if p.endswith("/embedding")][0])
        rel = np.abs(deq - orig).mean() / np.abs(orig).mean()
        assert rel < 0.02, rel

    @pytest.mark.parametrize("scan", [False, True])
    def test_depth_prune(self, tmp_path, scan):
        from paddlenlp_tpu.transformers import LlamaForCausalLM

        trainer = self._trainer(scan=scan)
        out = trainer.compress(strategy="prune_depth", output_dir=str(tmp_path / "d"),
                               depth_mult=0.5)
        pruned = LlamaForCausalLM.from_pretrained(out)
        assert pruned.config.num_hidden_layers == 2
        ids = jnp.asarray(np.arange(10)[None] % 90 + 3, jnp.int32)
        logits = pruned(input_ids=ids).logits
        assert logits.shape == (1, 10, 96)
        assert np.isfinite(np.asarray(logits)).all()
