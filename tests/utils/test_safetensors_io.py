"""safetensors IO: roundtrip (incl. 0-d), lazy slicing, sharding, bf16."""

import numpy as np
import pytest

from paddlenlp_tpu.utils.safetensors_io import SafeFile, load_file, safe_keys, save_file, shard_checkpoint


class TestRoundTrip:
    def test_basic(self, tmp_path):
        path = str(tmp_path / "t.safetensors")
        tensors = {
            "a": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b": np.ones((2,), dtype=np.int64),
            "c": np.asarray(True),
        }
        save_file(tensors, path)
        out = load_file(path)
        for k in tensors:
            np.testing.assert_array_equal(out[k], tensors[k])
            assert out[k].shape == tensors[k].shape

    def test_zero_dim_preserved(self, tmp_path):
        """Regression: np.ascontiguousarray promotes 0-d to 1-d; header/read must not."""
        path = str(tmp_path / "s.safetensors")
        save_file({"step": np.asarray(7, dtype=np.int32)}, path)
        out = load_file(path)["step"]
        assert out.shape == ()
        assert int(out) == 7

    def test_noncontiguous_input(self, tmp_path):
        path = str(tmp_path / "f.safetensors")
        arr = np.arange(24, dtype=np.float32).reshape(4, 6).T  # F-order view
        save_file({"x": arr}, path)
        np.testing.assert_array_equal(load_file(path)["x"], arr)

    def test_bf16(self, tmp_path):
        import ml_dtypes

        path = str(tmp_path / "bf.safetensors")
        arr = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16)
        save_file({"x": arr}, path)
        out = load_file(path)["x"]
        assert out.dtype == np.dtype(ml_dtypes.bfloat16)
        np.testing.assert_array_equal(out.astype(np.float32), arr.astype(np.float32))

    def test_interop_with_upstream_safetensors(self, tmp_path):
        """Files we write must parse with the official safetensors package and back."""
        from safetensors.numpy import load_file as hf_load, save_file as hf_save

        ours = str(tmp_path / "ours.safetensors")
        save_file({"w": np.ones((2, 3), dtype=np.float16)}, ours)
        theirs = hf_load(ours)
        np.testing.assert_array_equal(theirs["w"], np.ones((2, 3), dtype=np.float16))

        hf_path = str(tmp_path / "hf.safetensors")
        hf_save({"w": np.full((3,), 2.0, dtype=np.float32)}, hf_path)
        np.testing.assert_array_equal(load_file(hf_path)["w"], np.full((3,), 2.0, dtype=np.float32))


class TestLazySlicing:
    def test_get_slice_reads_subrange(self, tmp_path):
        path = str(tmp_path / "big.safetensors")
        arr = np.arange(1000, dtype=np.float32).reshape(100, 10)
        save_file({"x": arr}, path)
        with SafeFile(path) as sf:
            sl = sf.get_slice("x")
            assert sl.get_shape() == [100, 10]
            np.testing.assert_array_equal(sl[10:20], arr[10:20])
            np.testing.assert_array_equal(sl[:, 3], arr[:, 3])

    def test_keys(self, tmp_path):
        path = str(tmp_path / "k.safetensors")
        save_file({"a": np.zeros(1), "b": np.zeros(2)}, path)
        assert set(safe_keys(path)) == {"a", "b"}


class TestShardCheckpoint:
    def test_single_shard(self):
        shards, index = shard_checkpoint({"a": np.zeros(10, dtype=np.float32)})
        assert index is None and len(shards) == 1

    def test_multi_shard_index(self):
        tensors = {f"p{i}": np.zeros(256, dtype=np.float32) for i in range(8)}
        shards, index = shard_checkpoint(tensors, max_shard_size=1024 * 3)
        assert len(shards) > 1
        assert set(index["weight_map"]) == set(tensors)
        assert index["metadata"]["total_size"] == 8 * 256 * 4
