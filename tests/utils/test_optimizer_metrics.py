"""AdamWDL layer-wise decay, EMA, GLUE/squad metrics."""

import jax
import jax.numpy as jnp
import numpy as np


class TestAdamWDL:
    def test_depth_scaling(self):
        import optax

        from paddlenlp_tpu.ops.optimizer import adamwdl

        params = {"embed": {"kernel": jnp.ones((4, 4))},
                  "layers_0": {"kernel": jnp.ones((4, 4))},
                  "layers_3": {"kernel": jnp.ones((4, 4))},
                  "head": {"kernel": jnp.ones((4, 4))}}
        tx = adamwdl(1e-2, n_layers=4, layerwise_decay=0.5, weight_decay=0.0)
        state = tx.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        updates, _ = tx.update(grads, state, params)
        u = {k: float(jnp.abs(v["kernel"]).mean()) for k, v in updates.items()}
        assert u["head"] > u["layers_3"] > u["layers_0"] > u["embed"]
        np.testing.assert_allclose(u["layers_3"] / u["head"], 0.5, rtol=1e-3)

    def test_trains_a_model(self, tmp_path):
        from paddlenlp_tpu.ops.optimizer import adamwdl
        from paddlenlp_tpu.trainer import Trainer, TrainingArguments
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64,
                          use_scan_layers=False)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        rows = [np.random.default_rng(5).integers(0, 64, 12).astype(np.int32) for _ in range(64)]

        class DS:
            def __len__(self):
                return 64

            def __getitem__(self, i):
                return {"input_ids": rows[i], "labels": rows[i].copy()}

        args = TrainingArguments(output_dir=str(tmp_path), max_steps=4, per_device_train_batch_size=4,
                                 learning_rate=5e-3, logging_steps=1, save_strategy="no")
        tx = adamwdl(5e-3, n_layers=2, layerwise_decay=0.8)
        trainer = Trainer(model=model, args=args, train_dataset=DS(), optimizers=(tx, None))
        trainer.train()
        losses = [h["loss"] for h in trainer.state.log_history if "loss" in h]
        assert losses[-1] < losses[0], losses


class TestEMA:
    def test_shadow_tracks(self):
        from paddlenlp_tpu.ops.optimizer import ExponentialMovingAverage

        params = {"w": jnp.zeros(3)}
        ema = ExponentialMovingAverage(params, decay=0.5, debias=False)
        ema.update({"w": jnp.ones(3)})
        np.testing.assert_allclose(np.asarray(ema.state.shadow["w"]), 0.5)
        ema.update({"w": jnp.ones(3)})
        np.testing.assert_allclose(np.asarray(ema.state.shadow["w"]), 0.75)
        live = {"w": jnp.full(3, 9.0)}
        shadow = ema.apply(live)
        np.testing.assert_allclose(np.asarray(shadow["w"]), 0.75)
        assert ema.restore() is live


class TestGlueMetrics:
    def test_accuracy_f1(self):
        from paddlenlp_tpu.metrics import AccuracyAndF1

        m = AccuracyAndF1()
        m.update([1, 0, 1, 1], [1, 0, 0, 1])
        out = m.accumulate()
        np.testing.assert_allclose(out["accuracy"], 0.75)
        np.testing.assert_allclose(out["f1"], 2 * (2 / 3) * 1.0 / (2 / 3 + 1.0))

    def test_mcc_perfect(self):
        from paddlenlp_tpu.metrics import Mcc

        m = Mcc()
        m.update([1, 0, 1, 0], [1, 0, 1, 0])
        np.testing.assert_allclose(m.accumulate()["mcc"], 1.0)

    def test_pearson_spearman(self):
        from paddlenlp_tpu.metrics import PearsonAndSpearman

        m = PearsonAndSpearman()
        m.update([1.0, 2.0, 3.0, 4.0], [2.0, 4.0, 6.0, 8.0])
        out = m.accumulate()
        np.testing.assert_allclose(out["pearson"], 1.0, atol=1e-9)
        np.testing.assert_allclose(out["spearman"], 1.0, atol=1e-9)


class TestSquad:
    def test_em_f1(self):
        from paddlenlp_tpu.metrics import squad_evaluate

        examples = [{"id": "a", "answers": ["the cat sat"]},
                    {"id": "b", "answers": ["blue", "navy blue"]}]
        preds = {"a": "The cat sat.", "b": "dark navy blue"}
        out = squad_evaluate(examples, preds)
        assert out["exact"] == 50.0
        assert 50.0 < out["f1"] <= 100.0
