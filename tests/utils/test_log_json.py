"""Structured-JSON logging mode: one parseable JSON object per line with
level/ts/logger/msg, selectable via PDNLP_TPU_LOG_JSON."""

import importlib.util
import json
import logging
import os
import subprocess
import sys

LOG_PY = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "paddlenlp_tpu", "utils", "log.py")


def _load_log_module(name):
    # log.py is stdlib-only and relative-import-free: loading it straight from
    # its file skips the heavyweight package __init__
    spec = importlib.util.spec_from_file_location(name, LOG_PY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestJsonFormatter:
    def _record(self, msg="hello", exc=None):
        return logging.LogRecord(
            name="paddlenlp_tpu", level=logging.WARNING, pathname="/a/b/serving.py",
            lineno=42, msg=msg, args=(), exc_info=exc)

    def test_record_formats_as_json(self):
        mod = _load_log_module("_log_json_test")
        out = json.loads(mod._JsonFormatter().format(self._record()))
        assert out["level"] == "WARNING"
        assert out["logger"] == "paddlenlp_tpu"
        assert out["msg"] == "hello"
        assert out["file"] == "serving.py" and out["line"] == 42
        assert isinstance(out["ts"], float)

    def test_exception_lands_in_exc_key(self):
        mod = _load_log_module("_log_json_test2")
        try:
            raise ValueError("boom")
        except ValueError:
            rec = self._record(exc=sys.exc_info())
        out = json.loads(mod._JsonFormatter().format(rec))
        assert "ValueError: boom" in out["exc"]
        assert "\n" not in mod._JsonFormatter().format(rec)  # one line per event

    def test_env_var_selects_json_mode(self):
        # fresh interpreter so the env var is read at Logger construction;
        # log.py loads from file, keeping the subprocess light
        code = (
            "import importlib.util\n"
            f"spec = importlib.util.spec_from_file_location('l', {LOG_PY!r})\n"
            "m = importlib.util.module_from_spec(spec); spec.loader.exec_module(m)\n"
            "m.logger.warning('json mode works')\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=60,
            env={**os.environ, "PDNLP_TPU_LOG_JSON": "1"})
        line = proc.stderr.strip().splitlines()[-1]
        out = json.loads(line)
        assert out["msg"] == "json mode works" and out["level"] == "WARNING"

    def test_set_json_toggles_at_runtime(self):
        mod = _load_log_module("_log_json_test3")
        logger = mod.logger
        logger.set_json(True)
        assert isinstance(logger._handler.formatter, mod._JsonFormatter)
        logger.set_json(False)
        assert isinstance(logger._handler.formatter, mod._ColorFormatter)


class TestLogTraceCorrelation:
    """ISSUE 6: JSON log records carry the ambient span-tracer trace id, the
    grep-join key between fleet logs and stitched /debug/trace timelines.
    Uses the real package module (the standalone file load above cannot reach
    the tracer's contextvar)."""

    def _record(self):
        return logging.LogRecord(
            name="paddlenlp_tpu", level=logging.WARNING, pathname="/a/serving.py",
            lineno=1, msg="step", args=(), exc_info=None)

    def test_trace_key_inside_traced_request(self):
        from paddlenlp_tpu.observability import use_trace
        from paddlenlp_tpu.utils.log import _JsonFormatter

        with use_trace("rtr-42"):
            out = json.loads(_JsonFormatter().format(self._record()))
        assert out["trace"] == "rtr-42"

    def test_no_trace_key_outside_requests(self):
        from paddlenlp_tpu.utils.log import _JsonFormatter

        out = json.loads(_JsonFormatter().format(self._record()))
        assert "trace" not in out

    def test_nested_trace_wins(self):
        from paddlenlp_tpu.observability import use_trace
        from paddlenlp_tpu.utils.log import _JsonFormatter

        with use_trace("outer"), use_trace("inner"):
            out = json.loads(_JsonFormatter().format(self._record()))
        assert out["trace"] == "inner"
