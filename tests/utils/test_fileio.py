"""atomic_write contract: the target is always either old-complete or
new-complete — a crash (exception) mid-write changes nothing."""

import json
import os

import pytest

from paddlenlp_tpu.utils.fileio import atomic_write, fsync_dir, fsync_file


class TestAtomicWrite:
    def test_creates_new_file(self, tmp_path):
        p = tmp_path / "state.json"
        with atomic_write(str(p)) as f:
            json.dump({"step": 4}, f)
        assert json.loads(p.read_text()) == {"step": 4}

    def test_replaces_existing_atomically(self, tmp_path):
        p = tmp_path / "state.json"
        p.write_text("old")
        with atomic_write(str(p)) as f:
            f.write("new")
        assert p.read_text() == "new"

    def test_exception_leaves_target_untouched(self, tmp_path):
        p = tmp_path / "state.json"
        p.write_text('{"step": 2}')
        with pytest.raises(RuntimeError):
            with atomic_write(str(p)) as f:
                f.write('{"step": 4, "truncat')  # mid-payload crash
                raise RuntimeError("killed mid-save")
        assert json.loads(p.read_text()) == {"step": 2}  # old content intact

    def test_no_tmp_litter(self, tmp_path):
        p = tmp_path / "state.json"
        with atomic_write(str(p)) as f:
            f.write("ok")
        with pytest.raises(ValueError):
            with atomic_write(str(p)) as f:
                raise ValueError("boom")
        assert sorted(os.listdir(tmp_path)) == ["state.json"]

    def test_binary_mode(self, tmp_path):
        p = tmp_path / "blob.bin"
        with atomic_write(str(p), mode="wb") as f:
            f.write(b"\x00\x01\x02")
        assert p.read_bytes() == b"\x00\x01\x02"

    def test_fsync_helpers_tolerate_real_paths(self, tmp_path):
        p = tmp_path / "f"
        p.write_text("x")
        fsync_file(str(p))
        fsync_dir(str(tmp_path))  # best-effort; must not raise


class TestTrainerStateAtomicSave:
    def test_save_to_json_is_crash_safe(self, tmp_path, monkeypatch):
        """TrainerState.save_to_json goes through atomic_write: simulate a
        crash inside json.dump and verify the previous state file survives."""
        from paddlenlp_tpu.trainer.trainer_callback import TrainerState

        path = tmp_path / "trainer_state.json"
        TrainerState(global_step=6).save_to_json(str(path))
        assert TrainerState.load_from_json(str(path)).global_step == 6

        state = TrainerState(global_step=8)
        # make asdict explode after the file is opened
        monkeypatch.setattr("dataclasses.asdict",
                            lambda *_a, **_k: (_ for _ in ()).throw(OSError("died")))
        with pytest.raises(OSError):
            state.save_to_json(str(path))
        assert TrainerState.load_from_json(str(path)).global_step == 6
