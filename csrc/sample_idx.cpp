// Native helpers for pretraining dataset index construction.
//
// Counterpart of the reference's compiled dataset helpers used by
// paddlenlp/data/causal_dataset.py::_build_index_mappings (:417) — the
// O(total_epoch_tokens) sample-boundary walk is the only part of data prep that
// is too slow in Python for billion-token corpora.
//
// Built lazily by paddlenlp_tpu/data/native.py:
//   g++ -O3 -shared -fPIC -o libpdnlp_data.so sample_idx.cpp

#include <cstdint>

extern "C" {

// Walk documents (in doc_idx order, cycling epochs) and emit, for each training
// sample boundary, the (document position, within-document offset) pair.
//   sizes:      [n_seqs]   token count of each sequence
//   doc_idx:    [n_docs_total] shuffled document order (already epoch-repeated)
//   sample_idx: [ (n_samples+1) * 2 ] output: (doc_pos, doc_offset) per boundary
// Returns 0 on success, -1 if the corpus is exhausted before n_samples.
int build_sample_idx(const int32_t* sizes,
                     const int64_t* doc_idx,
                     int64_t n_docs_total,
                     int64_t seq_length,
                     int64_t n_samples,
                     int64_t* sample_idx) {
  int64_t doc_pos = 0;      // index into doc_idx
  int64_t doc_offset = 0;   // token offset within current document
  sample_idx[0] = doc_pos;
  sample_idx[1] = doc_offset;
  for (int64_t i = 1; i <= n_samples; ++i) {
    int64_t remaining = seq_length + 1;  // +1: targets are inputs shifted by one
    while (remaining > 0) {
      if (doc_pos >= n_docs_total) return -1;
      int64_t doc_len = sizes[doc_idx[doc_pos]] - doc_offset;
      if (doc_len > remaining) {
        doc_offset += remaining;
        remaining = 0;
      } else {
        remaining -= doc_len;
        ++doc_pos;
        doc_offset = 0;
      }
    }
    sample_idx[2 * i] = doc_pos;
    sample_idx[2 * i + 1] = doc_offset;
  }
  return 0;
}

// Weighted blend assignment (largest-deficit greedy, the Megatron
// build_blending_indices semantics): for each blended sample i, pick the
// component whose running count is furthest behind its quota.
//   weights:              [n_components], sum to 1
//   dataset_index:        [n_samples] out (component id)
//   dataset_sample_index: [n_samples] out (index within component)
void build_blending_indices(const double* weights,
                            int64_t n_components,
                            int64_t n_samples,
                            int32_t* dataset_index,
                            int64_t* dataset_sample_index) {
  int64_t* counts = new int64_t[n_components]();
  for (int64_t i = 0; i < n_samples; ++i) {
    double best = -1e18;
    int64_t best_c = 0;
    for (int64_t c = 0; c < n_components; ++c) {
      double deficit = (double)(i + 1) * weights[c] - (double)counts[c];
      if (deficit > best) {
        best = deficit;
        best_c = c;
      }
    }
    dataset_index[i] = (int32_t)best_c;
    dataset_sample_index[i] = counts[best_c];
    ++counts[best_c];
  }
  delete[] counts;
}

// Fisher-Yates shuffle with a splitmix64 PRNG (deterministic across platforms).
static inline uint64_t splitmix64(uint64_t* state) {
  uint64_t z = (*state += 0x9E3779B97f4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

void shuffle_int64(int64_t* arr, int64_t n, uint64_t seed) {
  uint64_t state = seed;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = (int64_t)(splitmix64(&state) % (uint64_t)(i + 1));
    int64_t tmp = arr[i];
    arr[i] = arr[j];
    arr[j] = tmp;
  }
}

}  // extern "C"
